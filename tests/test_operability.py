"""Operability subsystems: stats manager, flags, HTTP admin endpoints,
console rendering (ref common/stats/StatsManager, webservice/,
console/ — SURVEY §5)."""
import json
import urllib.request

import pytest

from nebula_tpu.common.flags import MUTABLE, IMMUTABLE, FlagRegistry
from nebula_tpu.common.stats import Duration, StatsManager
from nebula_tpu.console import Console, render_table
from nebula_tpu.webservice import WebService


# ---------------------------------------------------------------- stats

def test_stats_counter_windows():
    t = [1000.0]
    sm = StatsManager(clock=lambda: t[0])
    for _ in range(10):
        sm.add_value("qps")
    assert sm.read_stats("qps.count.60") == 10
    assert sm.read_stats("qps.sum.60") == 10
    assert sm.read_stats("qps.rate.60") == pytest.approx(10 / 60)
    # values age out of the 60 s window but stay in the 600 s one
    t[0] += 120
    sm.add_value("qps")
    assert sm.read_stats("qps.count.60") == 1
    assert sm.read_stats("qps.count.600") == 11
    assert sm.read_stats("qps.count.3600") == 11


def test_stats_avg_and_percentiles():
    t = [5000.0]
    sm = StatsManager(clock=lambda: t[0])
    for v in range(1, 101):
        sm.add_value("lat", float(v))
    assert sm.read_stats("lat.avg.60") == pytest.approx(50.5)
    # log-bucketed percentiles: approximate but ordered
    p50 = sm.read_stats("lat.p50.60")
    p95 = sm.read_stats("lat.p95.60")
    p99 = sm.read_stats("lat.p99.60")
    assert p50 <= p95 <= p99
    assert 30 <= p50 <= 80
    assert p99 >= 80


def test_stats_unknown_and_bad_specs():
    sm = StatsManager()
    assert sm.read_stats("nope.sum.60") is None
    sm.add_value("m")
    assert sm.read_stats("m.sum.61") is None       # bad window
    assert sm.read_stats("m.bogus.60") is None     # bad method
    assert sm.read_stats("m") is None


def test_duration_records_us():
    sm = StatsManager()
    d = Duration(sm, "op_us")
    us = d.record()
    assert us >= 0
    assert sm.read_stats("op_us.count.60") == 1


# ---------------------------------------------------------------- flags

def test_flags_declare_get_set_modes():
    fr = FlagRegistry("TEST")
    fr.declare("a", 1, MUTABLE)
    fr.declare("b", "x", IMMUTABLE)
    assert fr.get("a") == 1
    assert fr.set("a", 2)
    assert fr.get("a") == 2
    assert not fr.set("b", "y")      # immutable
    assert not fr.set("missing", 1)
    seen = []
    fr.watch(lambda n, v: seen.append((n, v)))
    fr.set("a", 3)
    assert seen == [("a", 3)]


def test_flags_meta_roundtrip():
    from nebula_tpu.meta.service import MetaService
    meta = MetaService()
    fr = FlagRegistry("GRAPHX")
    fr.declare("alpha", 10)
    fr.sync_to_meta(meta)
    # an operator changes the cluster config; the daemon pulls it
    assert meta.set_config("GRAPHX", "alpha", 42).ok()
    assert fr.pull_from_meta(meta) == 1
    assert fr.get("alpha") == 42


# ---------------------------------------------------------------- web

@pytest.fixture
def web():
    fr = FlagRegistry("WEB")
    fr.declare("knob", 5)
    sm = StatsManager()
    sm.add_value("hits", 3.0)
    ws = WebService("test-daemon", flags=fr, stats=sm)
    port = ws.start()
    yield ws, fr, sm, port
    ws.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def test_web_status(web):
    ws, fr, sm, port = web
    assert _get(port, "/status") == {"status": "running",
                                     "name": "test-daemon"}


def test_web_flags_get_and_put(web):
    ws, fr, sm, port = web
    assert _get(port, "/flags")["knob"]["value"] == 5
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/flags", data=b"knob=9", method="PUT")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read()) == {"knob": True}
    assert fr.get("knob") == 9


def test_web_get_stats(web):
    ws, fr, sm, port = web
    out = _get(port, "/get_stats?stats=hits.sum.60,hits.count.60")
    assert out["hits.sum.60"] == 3.0
    assert out["hits.count.60"] == 1.0


def test_web_404(web):
    ws, fr, sm, port = web
    with pytest.raises(urllib.error.HTTPError):
        _get(port, "/nope")


# ---------------------------------------------------------------- console

def test_render_table():
    out = render_table(["name", "age"], [["Tim", 42], ["Al", 7]])
    lines = out.splitlines()
    assert lines[0].startswith("+")
    assert "| name | age |" in lines[1]
    assert "| Tim  | 42  |" in out
    assert "| Al   | 7   |" in out


def test_console_batch(tmp_path, capsys):
    import io
    from nebula_tpu.cluster import InProcCluster
    cluster = InProcCluster()
    conn = cluster.connect()
    buf = io.StringIO()
    console = Console(conn, out=buf)
    assert console.run_statement(
        "CREATE SPACE cs(partition_num=1); USE cs;"
        "CREATE TAG t(name string)")
    assert console.run_statement(
        'INSERT VERTEX t(name) VALUES 1:("x")')
    assert console.run_statement("FETCH PROP ON t 1")
    text = buf.getvalue()
    assert "Execution succeeded" in text
    assert "x" in text
    assert not console.run_statement("exit")


def test_console_error_rendering():
    import io
    from nebula_tpu.cluster import InProcCluster
    cluster = InProcCluster()
    conn = cluster.connect()
    buf = io.StringIO()
    console = Console(conn, out=buf)
    console.run_statement("THIS IS NOT NGQL")
    assert "[ERROR" in buf.getvalue()


def test_flagfile_loading(tmp_path):
    """gflags-style flagfile (ref: etc/*.conf.default + --flagfile)."""
    from nebula_tpu.common.flags import FlagRegistry
    reg = FlagRegistry("TEST")
    reg.declare("an_int", 5)
    reg.declare("a_bool", False)
    reg.declare("a_str", "x")
    p = tmp_path / "test.conf"
    p.write_text("# comment\n\n--an_int=42\n--a_bool=true\n"
                 "--a_str=hello world\n--undeclared=7\n")
    assert reg.load_flagfile(str(p)) == 4
    assert reg.get("an_int") == 42
    assert reg.get("a_bool") is True
    assert reg.get("a_str") == "hello world"
    assert reg.get("undeclared") == "7"  # undeclared -> string flag


def test_default_flagfiles_parse():
    import os
    from nebula_tpu.common.flags import FlagRegistry
    etc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "etc")
    for f in os.listdir(etc):
        reg = FlagRegistry("X")
        assert reg.load_flagfile(os.path.join(etc, f)) > 0


def test_match_is_grammar_level_stub():
    """MATCH parses but reports unsupported (ref: MatchExecutor stub)."""
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.status import ErrorCode
    from nebula_tpu.parser import GQLParser, ast
    seq = GQLParser().parse("MATCH (v:player) RETURN v")
    assert seq.sentences[0].kind == ast.Kind.MATCH
    c = InProcCluster()
    conn = c.connect()
    r = conn.execute("MATCH (v:player) RETURN v")
    assert r.code == ErrorCode.E_UNSUPPORTED


def test_match_does_not_swallow_following_statements():
    from nebula_tpu.parser import GQLParser, ast
    seq = GQLParser().parse("MATCH (v:player) RETURN v; USE nba")
    assert [s.kind for s in seq.sentences] == [ast.Kind.MATCH, ast.Kind.USE]


def test_flagfile_bare_bool(tmp_path):
    from nebula_tpu.common.flags import FlagRegistry
    reg = FlagRegistry("TEST")
    reg.declare("daemonize", False)
    p = tmp_path / "f.conf"
    p.write_text("--daemonize\n--local_config\n")
    assert reg.load_flagfile(str(p)) == 2
    assert reg.get("daemonize") is True       # gflags: bare flag = true
    assert reg.get("local_config") is True


def test_cluster_id_heartbeat_gate():
    """ClusterIdMan parity: persisted id, mismatched heartbeats rejected
    (ref: meta/ClusterIdMan.h, HBProcessor clusterId check)."""
    from nebula_tpu.common.status import ErrorCode
    from nebula_tpu.meta.service import MetaService
    m = MetaService()
    cid = m.get_cluster_id()
    assert cid > 0
    assert m.heartbeat("h1:1", "storage").ok()            # first contact
    assert m.heartbeat("h1:1", "storage", cluster_id=cid).ok()
    st = m.heartbeat("h1:1", "storage", cluster_id=cid + 1)
    assert st.code == ErrorCode.E_WRONG_CLUSTER
    # persisted: a new service over the same store sees the same id
    m2 = MetaService(store=m._store)
    assert m2.get_cluster_id() == cid


def test_concurrent_lru_cache():
    from nebula_tpu.common.lru import ConcurrentLRUCache
    c = ConcurrentLRUCache(3)
    for i in range(5):
        c.put(i, i * 10)
    assert len(c) == 3
    assert c.get(0) is None and c.get(1) is None   # evicted, LRU order
    assert c.get(4) == 40
    c.get(2)                      # touch -> most recent
    c.put(9, 90)
    assert c.get(3) is None and c.get(2) == 20     # 3 evicted, 2 kept
    assert c.get_or_compute(7, lambda: 70) == 70
    assert c.evict(7) and not c.evict(7)


def test_storage_http_admin_endpoints():
    """HTTP admin parity: /status /admin?op=compact|flush /download
    /ingest on storaged (ref: StorageHttp*Handler)."""
    import json as _json
    import urllib.request
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.client import GraphClient
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, ws_port=0)
    graphd = serve_graphd(metad.addr, ws_port=0)
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE http_s(partition_num=2)", "USE http_s",
                  "CREATE TAG t(x int)", "INSERT VERTEX t(x) VALUES 1:(5)",
                  "INSERT VERTEX t(x) VALUES 1:(6)"):   # two versions
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        space_id = metad.meta.get_space("http_s").value().space_id

        def http(port, path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}") as resp:
                return resp.status, _json.loads(resp.read())

        for h in (metad, storaged, graphd):
            code, body = http(h.ws_port, "/status")
            assert code == 200 and body["status"] == "running"
        code, body = http(storaged.ws_port,
                          f"/admin?op=compact&space={space_id}")
        assert code == 200 and body["removed"] >= 1   # old version GC'd
        code, body = http(storaged.ws_port,
                          f"/admin?op=flush&space={space_id}")
        assert code == 200
        r = gc.execute("FETCH PROP ON t 1 YIELD t.x")
        assert r.ok() and r.rows[0][-1] == 6          # newest survives
    finally:
        graphd.stop(); storaged.stop(); metad.stop()


def test_admin_compact_drops_tombstones_and_old_versions():
    from nebula_tpu.cluster import InProcCluster
    c = InProcCluster()
    conn = c.connect()
    conn.must("CREATE SPACE gc_s(partition_num=2)")
    conn.must("USE gc_s")
    conn.must("CREATE TAG t(x int)")
    conn.must("CREATE EDGE e(w int)")
    conn.must("INSERT VERTEX t(x) VALUES 1:(1), 2:(2)")
    conn.must("INSERT VERTEX t(x) VALUES 1:(10)")     # second version
    conn.must("INSERT EDGE e(w) VALUES 1->2:(3)")
    conn.must("INSERT EDGE e(w) VALUES 1->2:(7)")     # second version (x2: fwd+rev)
    space_id = c.meta.get_space("gc_s").value().space_id
    st, removed = c.storage.admin_compact(space_id)
    # superseded: 1 vertex version + fwd and rev copies of the old edge
    assert st.ok() and removed == 3
    # semantics unchanged after physical GC
    r = conn.must("FETCH PROP ON t 1 YIELD t.x")
    assert r.rows[0][-1] == 10
    r = conn.must("GO FROM 1 OVER e YIELD e._dst AS d")
    assert r.rows == [(2,)]
    # second compact is a no-op
    st, removed2 = c.storage.admin_compact(space_id)
    assert st.ok() and removed2 == 0


def test_flagfile_bad_value_names_line(tmp_path):
    import pytest as _pt
    from nebula_tpu.common.flags import FlagRegistry
    reg = FlagRegistry("TEST")
    reg.declare("n", 5)
    p = tmp_path / "bad.conf"
    p.write_text("# ok\n--n=ten\n")
    with _pt.raises(ValueError, match=r"bad\.conf:2.*'n'"):
        reg.load_flagfile(str(p))


def test_cluster_id_file_pins_daemon(tmp_path):
    """A persisted cluster id detects pointing a daemon at the wrong
    metad (ref: on-disk cluster.id)."""
    from nebula_tpu.meta.client import MetaClient
    from nebula_tpu.daemons import serve_metad
    import time as _t
    cid_file = tmp_path / "cluster.id"
    m1 = serve_metad()
    m2 = serve_metad()
    try:
        mc = MetaClient(m1.addr, local_addr="x:1", role="storage",
                        cluster_id_file=str(cid_file))
        mc.start(heartbeat=True, watch_topology=False)
        for _ in range(50):
            if cid_file.exists():
                break
            _t.sleep(0.05)
        assert int(cid_file.read_text()) == m1.meta.get_cluster_id()
        mc.stop()
        # same id file, different cluster -> heartbeats refused & stop
        mc2 = MetaClient(m2.addr, local_addr="x:1", role="storage",
                         cluster_id_file=str(cid_file))
        mc2.start(heartbeat=True, watch_topology=False)
        for _ in range(50):
            if mc2.wrong_cluster:
                break
            _t.sleep(0.05)
        assert mc2.wrong_cluster
        mc2.stop()
    finally:
        m1.stop(); m2.stop()


def test_find_is_grammar_level_stub():
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.status import ErrorCode
    from nebula_tpu.parser import GQLParser, ast
    seq = GQLParser().parse("FIND name, age FROM player; YIELD 1 AS x")
    assert [s.kind for s in seq.sentences] == [ast.Kind.FIND, ast.Kind.YIELD]
    conn = InProcCluster().connect()
    r = conn.execute("FIND name FROM player")
    assert r.code == ErrorCode.E_UNSUPPORTED
    # FIND SHORTEST/ALL PATH still parses as a real statement
    seq = GQLParser().parse("FIND SHORTEST PATH FROM 1 TO 2 OVER like")
    assert seq.sentences[0].kind == ast.Kind.FIND_PATH


def test_graphd_tpu_stats_endpoint():
    """/tpu_stats on a --tpu graphd: serving counters, aggregation
    decline reasons and per-space budget fits, operator-visible over
    the HTTP admin surface."""
    import json as _json
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.engine_tpu import TpuGraphEngine

    metad = serve_metad()
    storaged = serve_storaged(metad.addr, load_interval=0.1)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE ts_s(partition_num=2)", "USE ts_s",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        r = gc.execute("GO FROM 1 OVER e YIELD e._dst")
        assert r.ok() and r.rows == [(2,)]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{graphd.ws_port}/tpu_stats") as resp:
            assert resp.status == 200
            body = _json.loads(resp.read())
        assert body["stats"]["go_served"] >= 1, body
        assert "agg_decline_reasons" in body
        assert isinstance(body["sparse_edge_budget"], int)
        # mesh serving matrix (mesh_exec.py): always present so
        # dashboards can alert on declined-on-mesh features; empty
        # dicts on this unmeshed graphd
        assert body["mesh"] == {"served": {}, "declined": {}}, body
        assert "budget_recalibrations" in body["stats"]
        # degradation ladder block (docs/manual/9-robustness.md)
        assert "breaker_trips" in body["robustness"], body
        assert body["breaker_state"] == body["robustness"]["breaker_state"]
        # /faults admin endpoint: arm a plan, observe it, clear it
        base = f"http://127.0.0.1:{graphd.ws_port}/faults"
        req = urllib.request.Request(
            base, data=b"plan=encode.rows:n=1", method="PUT")
        with urllib.request.urlopen(req) as resp:
            armed = _json.loads(resp.read())
        assert "encode.rows" in armed["active"], armed
        assert "kernel.launch" in armed["points"]
        with urllib.request.urlopen(base + "?clear=1") as resp:
            cleared = _json.loads(resp.read())
        assert cleared["active"] == {}, cleared
        # /qos admin endpoint (docs/manual/14-qos.md): arm a plan,
        # observe per-tenant slices + the dispatcher lane block, pin a
        # session lane, clear
        assert "qos" in body and "admission" in body["qos"], body
        assert "lane_rounds" in body["qos"]["dispatcher"]
        qbase = f"http://127.0.0.1:{graphd.ws_port}/qos"
        req = urllib.request.Request(
            qbase, data=b"plan=ts_s:rate=1,burst=1,lane=bulk",
            method="PUT")
        with urllib.request.urlopen(req) as resp:
            qarmed = _json.loads(resp.read())
        assert qarmed["admission"]["armed"] is True
        assert qarmed["admission"]["spaces"]["ts_s"]["policy"][
            "lane"] == "bulk"
        # the armed budget actually throttles: burn the burst token,
        # then the next data statement is a typed retryable overload
        gc.execute("GO FROM 1 OVER e YIELD e._dst")
        r = gc.execute("GO FROM 1 OVER e YIELD e._dst")
        from nebula_tpu.common.status import ErrorCode
        assert r.code == ErrorCode.E_OVERLOAD, (r.code, r.error_msg)
        assert "retry" in r.error_msg
        # session lane pin through the endpoint
        sess_id = next(iter(graphd.service.sessions._sessions))
        req = urllib.request.Request(
            qbase, data=f"session={sess_id}:interactive".encode(),
            method="PUT")
        with urllib.request.urlopen(req):
            pass
        assert graphd.service.sessions.find(sess_id).value() \
            .qos_lane == "interactive"
        req = urllib.request.Request(
            qbase, data=f"session={sess_id}:".encode(), method="PUT")
        with urllib.request.urlopen(req):
            pass
        assert graphd.service.sessions.find(sess_id).value() \
            .qos_lane is None
        with urllib.request.urlopen(qbase + "?clear=1") as resp:
            qcleared = _json.loads(resp.read())
        assert qcleared["admission"]["armed"] is False
        r = gc.execute("GO FROM 1 OVER e YIELD e._dst")
        assert r.ok(), r.error_msg
        # bad plan / bad session are 400s, state untouched — including
        # the half-apply shape (valid plan + bad session must apply
        # NEITHER: a 400 means nothing changed)
        for bad in (b"plan=x:warp=1", b"session=zap:bulk",
                    b"nonsense=1", b"plan=ts_s:rate=1&session=zap:bulk"):
            req = urllib.request.Request(qbase, data=bad, method="PUT")
            try:
                urllib.request.urlopen(req)
                assert False, f"{bad!r} should have been rejected"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        with urllib.request.urlopen(qbase) as resp:
            assert _json.loads(resp.read())["admission"][
                "armed"] is False
    finally:
        from nebula_tpu.common.qos import admission
        admission.reset()
        graphd.stop(); storaged.stop(); metad.stop()


def test_observability_endpoints_3daemon():
    """Acceptance (ISSUE 4): PROFILE GO over the real graphd→storaged
    RPC boundary returns identical rows plus a span tree whose leaves
    include a dispatcher-window span and at least one storaged-side
    child span joined by trace_id; /traces, /queries and /metrics
    serve on BOTH graphd and storaged."""
    import json as _json
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.common.tracing import tracer
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # this test is ABOUT the dispatcher-window span tree: pin the
    # engine to the dispatcher path (cluster scatter/gather v2 serves
    # plain GO without a graphd-local window — its spans are covered
    # by test_device_serve)
    graph_flags.set("cluster_device_serve", False)
    metad = serve_metad()
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)

    def http(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read()
            return (body if "json" not in ctype
                    else _json.loads(body)), r.status

    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE obs(partition_num=2)", "USE obs",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = ("GO 2 STEPS FROM 1 OVER e YIELD e.w AS w "
             "| YIELD $-.w AS w")
        gc.execute(q)                 # snapshot up
        # an INSERT right before the PROFILE makes the traced query
        # pull the change feed / rebuild over the storage RPC — the
        # storaged-side child spans land inside THIS trace (a warm
        # snapshot needs zero storaged RPCs by design)
        r = gc.execute("INSERT EDGE e(w) VALUES 3 -> 1:(9)")
        assert r.ok(), r.error_msg
        prof = plain = None
        for _ in range(40):           # version-watch push is async
            import time as _time
            _time.sleep(0.05)
            prof = gc.execute("PROFILE " + q)
            assert prof.ok(), prof.error_msg
            names = [s[2] for s in prof.trace_spans or ()]
            if "dispatcher.window" in names and any(
                    n.startswith(("storage.", "proc."))
                    for n in names):
                break
            r = gc.execute("INSERT EDGE e(w) VALUES 3 -> 2:(8)")
            assert r.ok(), r.error_msg
        plain = gc.execute(q)
        assert plain.ok() and prof.ok(), (plain.error_msg,
                                          prof.error_msg)
        assert sorted(plain.rows) == sorted(prof.rows)  # identical
        assert prof.trace_id and prof.trace_spans
        names = [s[2] for s in prof.trace_spans]
        # the TPU-served traversal went through the dispatcher...
        assert "dispatcher.window" in names, names
        # ...and the trace crossed the RPC boundary: at least one
        # storaged-side child span (the adopted storage.<method> root
        # and/or its proc.* children), joined to the same tree
        storaged_side = [s for s in prof.trace_spans
                         if s[2].startswith(("storage.", "proc."))]
        assert storaged_side, names
        ids = {s[0] for s in prof.trace_spans}
        assert all(s[1] in ids for s in storaged_side), \
            "remote spans must join the local tree"
        # /traces on graphd: summary list + get-by-id + arm knob
        body, st = http(graphd.ws_port, "/traces")
        assert st == 200 and any(
            t["trace_id"] == prof.trace_id for t in body["traces"])
        body, st = http(graphd.ws_port, f"/traces?id={prof.trace_id}")
        assert st == 200 and len(body["spans"]) == len(prof.trace_spans)
        body, st = http(graphd.ws_port, "/traces?arm=3")
        assert body == {"armed": 3}
        r = gc.execute(q)                   # armed: sampled, no attach
        assert r.ok() and r.trace_spans is None
        assert tracer.armed() == 2
        http(graphd.ws_port, "/traces?arm=0")
        # /traces on storaged: the remote fragments it recorded
        body, st = http(storaged.ws_port, "/traces")
        assert st == 200 and any(t.get("remote_fragment")
                                 for t in body["traces"]), body
        # /queries serves on both (graphd also carries the slow log)
        body, st = http(graphd.ws_port, "/queries")
        assert st == 200 and "active" in body and "slow" in body
        body, st = http(storaged.ws_port, "/queries")
        assert st == 200 and body["active"] == []
        # /metrics: Prometheus text exposition on all three daemons
        for port in (graphd.ws_port, storaged.ws_port, metad.ws_port):
            if port is None:
                continue
            body, st = http(port, "/metrics")
            assert st == 200 and isinstance(body, bytes)
        text = http(graphd.ws_port, "/metrics")[0].decode()
        # OpenMetrics family declaration: TYPE names the BASE, the
        # counter sample carries the _total suffix
        assert "# TYPE nebula_graph_query counter" in text
        assert "nebula_graph_query_total" in text
        assert "nebula_tpu_engine_go_served" in text
        # counters don't emit meaningless percentiles; histograms
        # expose native bucket series + window gauges
        assert "nebula_graph_query_p95_60s" not in text
        assert "# TYPE nebula_graph_query_latency_us histogram" in text
        assert 'nebula_graph_query_latency_us_bucket{le="+Inf"}' in text
        assert "nebula_graph_query_latency_us_p95_60s" in text
        # the fleet join key + uptime gauge ride every daemon's scrape
        assert 'nebula_build_info{daemon="graphd"' in text
        assert "nebula_process_uptime_seconds" in text
        assert text.rstrip().endswith("# EOF")
        stext = http(storaged.ws_port, "/metrics")[0].decode()
        # the snapshot sync hit the storage processors (get_bound only
        # fires on the CPU fan-out path, which the engine avoided)
        assert "nebula_storage_scan_part_qps_total" in stext
    finally:
        graph_flags.set("cluster_device_serve", True)
        graphd.stop(); storaged.stop(); metad.stop()


def test_cost_ledger_and_cluster_metrics_3daemon():
    """Acceptance (ISSUE 12): PROFILE over the real graphd→storaged
    boundary returns a `cost` block (per-host rows_scanned, rpc
    bytes) next to the span tree with byte-identical rows; the
    critical-path analyzer serves at /traces?critpath=<id>; slow
    queries carry their ledger on BOTH daemons; and graphd's
    /cluster_metrics federates all three roles into one strict
    OpenMetrics document."""
    import json as _json
    import time as _time
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.engine_tpu import TpuGraphEngine
    import openmetrics

    # queue_wait_us is charged by the DISPATCHER; pin the engine to
    # that path (the cluster scatter/gather serve has no graphd-local
    # window to queue behind)
    graph_flags.set("cluster_device_serve", False)
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)

    def http(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read()
            return (body if "json" not in ctype
                    else _json.loads(body)), r.status

    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE costspace(partition_num=2)",
                  "USE costspace",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = "GO 2 STEPS FROM 1 OVER e YIELD e.w AS w"
        gc.execute(q)                 # snapshot warm
        r = gc.execute("INSERT EDGE e(w) VALUES 3 -> 1:(9)")
        assert r.ok(), r.error_msg
        prof = None
        for _ in range(40):
            _time.sleep(0.05)
            prof = gc.execute("PROFILE " + q)
            assert prof.ok(), prof.error_msg
            cost = (prof.profile or {}).get("cost", {})
            # the INSERT forces the traced query to pull the change
            # feed over the storage RPC: server-side charges appear
            if cost.get("rows_scanned", 0) > 0:
                break
            r = gc.execute("INSERT EDGE e(w) VALUES 3 -> 2:(8)")
            assert r.ok(), r.error_msg
        plain = gc.execute(q)
        assert plain.ok()
        assert sorted(plain.rows) == sorted(prof.rows)
        cost = prof.profile["cost"]
        # the ledger crossed the RPC boundary: round trips + payload
        # bytes + server-side rows, attributed to the storaged host
        assert cost["rpc_calls"] > 0
        assert cost["rpc_bytes_out"] > 0 and cost["rpc_bytes_in"] > 0
        assert cost["rows_scanned"] > 0
        assert cost["hosts"], cost
        assert any(h.get("rows_scanned", 0) > 0
                   for h in cost["hosts"].values()), cost
        # queue wait is charged by the dispatcher for every GO
        assert cost["queue_wait_us"] > 0
        # critical-path attribution over the same trace
        body, st = http(graphd.ws_port,
                        f"/traces?critpath={prof.trace_id}")
        assert st == 200
        assert body["wall_us"] > 0 and body["critical_path"]
        assert 0.0 <= body["explained"] <= 1.0
        assert any(row["name"] == "query"
                   for row in body["critical_path"])
        # slow-query ledgers on both daemons: drop the threshold so
        # everything qualifies, then drive one more traced pull
        # (per-registry: graphd reads graph_flags, storaged its own
        # storage_flags twin)
        from nebula_tpu.common.flags import storage_flags
        graph_flags.set("slow_query_threshold_ms", 0.0001)
        storage_flags.set("slow_query_threshold_ms", 0.0001)
        try:
            r = gc.execute("INSERT EDGE e(w) VALUES 2 -> 1:(7)")
            assert r.ok()
            slow_st = None
            for _ in range(40):
                _time.sleep(0.05)
                gc.execute(q)
                slow_st = http(storaged.ws_port, "/queries")[0]["slow"]
                if slow_st:
                    break
            assert slow_st and "cost" in slow_st[0], slow_st
            slow_g = http(graphd.ws_port, "/queries")[0]["slow"]
            assert slow_g and "cost" in slow_g[0], slow_g
        finally:
            graph_flags.set("slow_query_threshold_ms", 500)
            storage_flags.set("slow_query_threshold_ms", 500)
        # /cluster_metrics: all three roles federated, strict-parsed
        doc = http(graphd.ws_port, "/cluster_metrics")[0].decode()
        fams = openmetrics.parse(doc)
        scrape = fams["nebula_cluster_scrape"]
        roles = {s.labels["role"]: s.value for s in scrape.samples}
        assert set(roles) == {"graph", "storage", "meta"}, roles
        assert all(v == 1 for v in roles.values()), roles
        # per-instance families carry the instance label end-to-end
        bi = fams["nebula_build_info"]
        assert {s.labels.get("role") for s in bi.samples} >= \
            {"graph", "storage", "meta"}
        # the cost rollups scrape as native histogram families
        assert any(name.startswith("nebula_graph_cost_")
                   for name in fams), sorted(fams)[:20]
        # nebtop consumes the same document (--once, machine form)
        from nebula_tpu.tools import nebtop
        snap = nebtop.Snapshot(nebtop.parse_samples(doc), t=0.0)
        insts = snap.instances()
        assert len(insts) == 3 and all(i["up"] for i in insts)
        assert snap.sum("nebula_graph_query_total") > 0
    finally:
        graph_flags.set("cluster_device_serve", True)
        graphd.stop(); storaged.stop(); metad.stop()


def test_profile_endpoints_3daemon():
    """Acceptance (ISSUE 13): /profile, /profile?locks=1 and
    /profile?compiles=1 serve end-to-end on graphd + storaged + metad;
    the always-on sampler attributes self-time per named thread role,
    ?format=collapsed emits flamegraph input, ?seconds=N captures on
    demand, and the engine's serve path shows up in the lock table."""
    import json as _json
    import time as _time
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # the compile table + engine_snapshot lock contention this test
    # asserts live on the graphd-local fused serve path — pin it (the
    # cluster scatter/gather serve compiles on the storaged tier)
    graph_flags.set("cluster_device_serve", False)
    metad = serve_metad(ws_port=0)
    storaged = serve_storaged(metad.addr, load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)

    def http(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read()
            return (body if "json" not in ctype
                    else _json.loads(body)), r.status

    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE profspace(partition_num=2)",
                  "USE profspace",
                  "CREATE TAG t(x int)", "CREATE EDGE e(w int)",
                  "INSERT VERTEX t(x) VALUES 1:(5), 2:(6), 3:(7)",
                  "INSERT EDGE e(w) VALUES 1 -> 2:(3), 2 -> 3:(4)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        q = "GO 2 STEPS FROM 1 OVER e YIELD e.w AS w"
        for _ in range(20):
            if gc.execute(q).rows:
                break
            _time.sleep(0.05)
        # force the dense device dispatch (a 2-edge toy graph routes
        # through the host sparse pull otherwise) and coalesce a
        # window, so the fused-program registry compiles — the
        # /profile?compiles=1 table's source
        tpu.sparse_edge_budget = 0
        import threading as _threading
        gcs = [GraphClient(graphd.addr).connect() for _ in range(3)]
        for c in gcs:
            assert c.execute("USE profspace").ok()
        from nebula_tpu.common import profiler as _prof
        for _ in range(10):
            ts = [_threading.Thread(target=lambda c=c: c.execute(q),
                                    name="prof-e2e-worker")
                  for c in gcs]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if _prof.compiles.totals()["compiles"]:
                break
        # the sampler runs at profile_hz (armed by WebService.start);
        # give it a few ticks so the window has samples to serve
        deadline = _time.monotonic() + 5
        body = None
        while _time.monotonic() < deadline:
            gc.execute(q)
            body, st = http(graphd.ws_port, "/profile")
            assert st == 200
            if body["samples"] > 0 and body["frames"]:
                break
            _time.sleep(0.1)
        assert body["samples"] > 0 and body["frames"], body["state"]
        assert body["state"]["thread_alive"]
        assert body["state"]["hz"] > 0
        # per-role attribution: daemon threads carry stable role names
        # (digit runs normalized; anonymous stdlib spawns resolve to
        # their target hint) — never a bare Thread-N
        assert body["threads"], body
        assert not any(r == "Thread-N" for r in body["threads"]), \
            body["threads"]
        # the three surfaces serve on EVERY daemon
        for port in (graphd.ws_port, storaged.ws_port, metad.ws_port):
            j, st = http(port, "/profile")
            assert st == 200 and "frames" in j and "state" in j
            j, st = http(port, "/profile?locks=1")
            assert st == 200 and isinstance(j["locks"], list)
            j, st = http(port, "/profile?compiles=1")
            assert st == 200 and "totals" in j
        # the serve-path lock sites registered (engine snapshot lock,
        # dispatcher cv wired through profiled locks at construction)
        j, _ = http(graphd.ws_port, "/profile?locks=1")
        names = {row["name"] for row in j["locks"]}
        assert {"engine_snapshot", "dispatcher_cv"} & names or \
            {"kv_part", "raft_part"} & names, names
        # fused programs compiled for the GO path -> compile table
        j, _ = http(graphd.ws_port, "/profile?compiles=1")
        assert j["totals"]["compiles"] >= 1, j["totals"]
        assert any(row["total_us"] > 0 for row in j["compiles"])
        # collapsed flamegraph output: "role;frame;... count" lines
        raw, st = http(graphd.ws_port, "/profile?format=collapsed")
        assert st == 200
        lines = [ln for ln in raw.decode().splitlines() if ln]
        assert lines
        stack, _, count = lines[0].rpartition(" ")
        assert ";" in stack and int(count) > 0
        # on-demand high-rate capture is bounded and private
        j, st = http(graphd.ws_port, "/profile?seconds=0.2&hz=97")
        assert st == 200 and j["samples"] > 0 and j["frames"]
        # role filter narrows the aggregation
        role = next(iter(body["threads"]))
        j, st = http(graphd.ws_port,
                     "/profile?thread=" + urllib.parse.quote(role))
        assert st == 200
        assert set(j["threads"]) <= {role}
    finally:
        graph_flags.set("cluster_device_serve", True)
        graphd.stop(); storaged.stop(); metad.stop()


def test_heat_observatory_3daemon(tmp_path):
    """Acceptance (ISSUE 14): the workload & data observatory proven
    e2e on a real 3-daemon topology — /heat serves on graphd AND
    storaged with populated slabs/sketches, the heartbeat carries the
    leaders' heat + staleness to metad, SHOW HOSTS gains the Leader
    heat column and SHOW PARTS the Heat/Staleness columns, BALANCE
    DATA heat returns the advisory table, metad's /balance?heat=1
    reports the modeled plan, and /cluster_metrics federates the
    nebula_part_heat_* families from both roles."""
    import json as _json
    import time
    import urllib.request
    from nebula_tpu.client import GraphClient
    from nebula_tpu.common import heat as heat_mod
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    heat_mod.accountant.reset()
    old_hb = storage_flags.get("heartbeat_interval_secs")
    storage_flags.set("heartbeat_interval_secs", 0.2)
    graph_flags.set("heat_vertices_k", 32)
    storage_flags.set("heat_vertices_k", 32)
    metad = serve_metad(ws_port=0)
    s0 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s0"),
                        load_interval=0.1, ws_port=0)
    s1 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s1"),
                        load_interval=0.1, ws_port=0)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu, ws_port=0)

    def http(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return _json.loads(r.read()), r.status

    try:
        gc = GraphClient(graphd.addr).connect()
        r = gc.execute("CREATE SPACE heatobs(partition_num=4, "
                       "replica_factor=2)")
        assert r.ok(), r.error_msg
        assert gc.execute("USE heatobs").ok()
        for s in ("CREATE TAG t(x int)", "CREATE EDGE e(w int)"):
            assert gc.execute(s).ok()
        deadline = time.time() + 15
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX t(x) VALUES " + ", ".join(
                f"{i}:({i})" for i in range(16)))
            if r.ok():
                break
            time.sleep(0.2)
        assert r.ok(), r.error_msg
        assert gc.execute("INSERT EDGE e(w) VALUES " + ", ".join(
            f"{i} -> {(i + 1) % 16}:({i})" for i in range(16))).ok()
        q = "GO 2 STEPS FROM 1 OVER e YIELD e._dst"
        for _ in range(40):
            if gc.execute(q).rows:
                break
            time.sleep(0.1)
        for i in range(24):
            gc.execute(f"GO 2 STEPS FROM {i % 4} OVER e "
                       f"YIELD e._dst")

        # ---- /heat on graphd: slabs + skew + sketches + degrees
        body, st = http(graphd.ws_port, "/heat?vertices=1")
        assert st == 200 and body["enabled"]
        assert body["parts"], body
        assert body["skew"]
        assert body["vertices"]["spaces"]
        assert any(s["top"] for s in body["vertices"]["spaces"]
                   .values())
        assert "degrees" in body["vertices"]
        # ---- /heat on storaged: slabs + the staleness watermarks
        sid = metad.meta.get_space("heatobs").value().space_id
        stale_rows = []
        for sd in (s0, s1):
            body, st = http(sd.ws_port, "/heat")
            assert st == 200 and body["enabled"]
            stale_rows.extend(body.get("staleness", []))
        # at least one leader reports populated per-replica watermarks
        assert stale_rows
        for row in stale_rows:
            assert row["replicas"], row
            for m in row["replicas"]:
                assert m["applied"] <= m["commit"], m
                assert m["match"] >= m["applied"], m
                assert m["staleness_ms"] >= 0, m
        # ---- /raft carries per-replica watermarks on leaders
        for sd in (s0, s1):
            body, st = http(sd.ws_port, "/raft")
            leads = [p for p in body["parts"]
                     if p["role"] == "LEADER"]
            for p in leads:
                assert "replicas" in p and "staleness_ms" in p

        # ---- heartbeat carry -> SHOW HOSTS / SHOW PARTS columns
        deadline = time.time() + 10
        rows = []
        while time.time() < deadline:
            r = gc.execute("SHOW PARTS")
            assert r.ok(), r.error_msg
            assert r.columns == ["Partition ID", "Leader", "Peers",
                                 "Losts", "Heat", "Staleness ms"]
            rows = r.rows
            if any(row[4] > 0 for row in rows):
                break
            time.sleep(0.3)
        assert any(row[4] > 0 for row in rows), rows
        r = gc.execute("SHOW HOSTS")
        assert r.ok()
        assert r.columns[-1] == "Leader heat"
        assert any(row[-1] > 0 for row in r.rows), r.rows

        # ---- the advisor surfaces: statement + metad endpoint
        r = gc.execute("BALANCE DATA heat")
        assert r.ok(), r.error_msg
        assert r.columns == ["Kind", "Detail", "Heat", "Planned"]
        kinds = {row[0] for row in r.rows}
        assert "host" in kinds and "spread" in kinds
        body, st = http(metad.ws_port, "/balance?heat=1")
        assert st == 200 and body["advisory"] is True
        assert set(body["current"])    # per-host heat present

        # ---- /cluster_metrics federates the heat families
        with urllib.request.urlopen(
                f"http://127.0.0.1:{graphd.ws_port}/cluster_metrics"
                ) as resp:
            doc = resp.read().decode()
        assert "nebula_part_heat_" in doc
        assert "nebula_heat_skew_index_" in doc
        insts = set()
        for line in doc.splitlines():
            if line.startswith("nebula_part_heat_") and \
                    "instance=" in line:
                insts.add(line.split('instance="', 1)[1]
                          .split('"', 1)[0])
        assert len(insts) >= 2, insts   # graphd + a storaged
    finally:
        graphd.stop()
        s0.stop()
        s1.stop()
        metad.stop()
        storage_flags.set("heartbeat_interval_secs", old_hb)
        graph_flags.set("heat_vertices_k", 0)
        storage_flags.set("heat_vertices_k", 0)
        heat_mod.accountant.reset()
