"""Parser tests (parity model: parser/test/ParserTest.cpp — every statement
family parses and round-trips via to_string())."""
import pytest

from nebula_tpu.parser import GQLParser, ParseError
from nebula_tpu.parser import ast


def parse(text):
    return GQLParser().parse(text)


def parse1(text):
    seq = parse(text)
    assert len(seq.sentences) == 1
    return seq.sentences[0]


# --- traversals ------------------------------------------------------------

def test_go_minimal():
    s = parse1("GO FROM 1 OVER like")
    assert isinstance(s, ast.GoSentence)
    assert s.step.steps == 1
    assert [v.to_string() for v in s.from_.vids] == ["1"]
    assert s.over.edges[0].name == "like"
    assert s.over.direction == ast.Direction.OUT


def test_go_full():
    s = parse1('GO 3 STEPS FROM 1, 2, 3 OVER like, serve REVERSELY '
               'WHERE like.likeness > 90 '
               'YIELD DISTINCT like._dst AS id, $^.player.name')
    assert s.step.steps == 3
    assert len(s.from_.vids) == 3
    assert [e.name for e in s.over.edges] == ["like", "serve"]
    assert s.over.direction == ast.Direction.IN
    assert s.where is not None
    assert s.yield_.distinct
    assert s.yield_.columns[0].alias == "id"


def test_go_over_star_bidirect():
    s = parse1("GO FROM 1 OVER * BIDIRECT")
    assert s.over.is_all
    assert s.over.direction == ast.Direction.BOTH


def test_go_from_input_ref():
    s = parse1("GO FROM $-.id OVER like")
    assert s.from_.ref is not None
    assert s.from_.ref.to_string() == "$-.id"


def test_pipe_and_variable():
    s = parse1("GO FROM 1 OVER like YIELD like._dst AS id | GO FROM $-.id OVER serve")
    assert isinstance(s, ast.PipedSentence)
    seq = parse("$var = GO FROM 1 OVER like; GO FROM $var.id OVER serve")
    assert isinstance(seq.sentences[0], ast.AssignmentSentence)
    assert seq.sentences[0].var == "var"
    assert len(seq.sentences) == 2


def test_find_path():
    s = parse1("FIND SHORTEST PATH FROM 1 TO 2 OVER like UPTO 4 STEPS")
    assert isinstance(s, ast.FindPathSentence)
    assert s.shortest and s.step.steps == 4
    s = parse1("FIND ALL PATH FROM 1 TO 2, 3 OVER *")
    assert not s.shortest
    assert s.over.is_all


def test_fetch_vertices_and_edges():
    s = parse1("FETCH PROP ON player 1, 2 YIELD player.name")
    assert isinstance(s, ast.FetchVerticesSentence)
    assert s.tag == "player"
    s = parse1("FETCH PROP ON like 1->2@0, 3->4")
    assert isinstance(s, ast.FetchEdgesSentence)
    assert len(s.keys) == 2
    assert s.keys[0].rank == 0
    s = parse1("FETCH PROP ON * 1")
    assert s.tag == "*"


def test_set_ops():
    s = parse1("GO FROM 1 OVER like UNION GO FROM 2 OVER like MINUS GO FROM 3 OVER like")
    assert isinstance(s, ast.SetSentence)
    assert s.op == ast.SetOp.MINUS
    assert isinstance(s.left, ast.SetSentence)
    assert s.left.op == ast.SetOp.UNION_DISTINCT  # bare UNION = DISTINCT
    s2 = parse1("GO FROM 1 OVER e UNION ALL GO FROM 2 OVER e")
    assert s2.op == ast.SetOp.UNION
    s3 = parse1("GO FROM 1 OVER e UNION DISTINCT GO FROM 2 OVER e")
    assert s3.op == ast.SetOp.UNION_DISTINCT


def test_order_by_limit_group_by():
    s = parse1("ORDER BY $-.age DESC, $-.name")
    assert isinstance(s, ast.OrderBySentence)
    assert not s.factors[0].ascending and s.factors[1].ascending
    s = parse1("LIMIT 3, 10")
    assert s.offset == 3 and s.count == 10
    s = parse1("GROUP BY $-.team YIELD $-.team, COUNT(*) AS cnt, AVG($-.age) AS avg_age")
    assert isinstance(s, ast.GroupBySentence)
    cols = s.yield_.columns
    assert cols[1].agg_fun == "COUNT" and cols[1].alias == "cnt"
    assert cols[2].agg_fun == "AVG"


def test_yield_standalone():
    s = parse1("YIELD 1 + 1 AS sum, hash(\"x\") AS h")
    assert isinstance(s, ast.YieldSentence)
    assert s.yield_.columns[0].alias == "sum"


# --- DDL -------------------------------------------------------------------

def test_create_space():
    s = parse1("CREATE SPACE nba(partition_num=10, replica_factor=3)")
    assert isinstance(s, ast.CreateSpaceSentence)
    assert s.partition_num == 10 and s.replica_factor == 3
    s = parse1("CREATE SPACE IF NOT EXISTS nba")
    assert s.if_not_exists


def test_create_tag_edge():
    s = parse1('CREATE TAG player(name string, age int DEFAULT 0)')
    assert isinstance(s, ast.CreateSchemaSentence)
    assert not s.is_edge
    assert [c.name for c in s.columns] == ["name", "age"]
    assert s.columns[1].default == 0
    s = parse1("CREATE EDGE like(likeness double) TTL_DURATION = 100, TTL_COL = \"ts\"")
    assert s.is_edge
    assert s.opts.ttl_duration == 100 and s.opts.ttl_col == "ts"
    s = parse1("CREATE TAG empty_tag()")
    assert s.columns == []


def test_alter_schema():
    s = parse1("ALTER TAG player ADD (height double), DROP (age)")
    assert isinstance(s, ast.AlterSchemaSentence)
    assert s.adds[0].name == "height"
    assert s.drops == ["age"]
    s = parse1("ALTER EDGE like CHANGE (likeness int)")
    assert s.changes[0].type_name == "INT"


def test_drop_describe():
    assert isinstance(parse1("DROP TAG player"), ast.DropSchemaSentence)
    assert isinstance(parse1("DESCRIBE EDGE like"), ast.DescribeSchemaSentence)
    assert isinstance(parse1("DESC SPACE nba"), ast.DescribeSpaceSentence)
    assert isinstance(parse1("DROP SPACE IF EXISTS nba"), ast.DropSpaceSentence)


# --- DML -------------------------------------------------------------------

def test_insert_vertex():
    s = parse1('INSERT VERTEX player(name, age) VALUES '
               '100:("Tim Duncan", 42), 101:("Tony Parker", 36)')
    assert isinstance(s, ast.InsertVerticesSentence)
    assert s.tag_items == [("player", ["name", "age"])]
    assert len(s.rows) == 2
    vid, vals = s.rows[0]
    assert vid.to_string() == "100"
    assert vals[0].value == "Tim Duncan"


def test_insert_vertex_multi_tag():
    s = parse1('INSERT VERTEX player(name), star(rank) VALUES 1:("a", 5)')
    assert len(s.tag_items) == 2


def test_insert_edge():
    s = parse1("INSERT EDGE like(likeness) VALUES 100 -> 101@7:(95.0), 100 -> 102:(90.0)")
    assert isinstance(s, ast.InsertEdgesSentence)
    src, dst, rank, vals = s.rows[0]
    assert rank == 7
    assert s.rows[1][2] == 0
    assert vals[0].value == 95.0


def test_insert_with_uuid_and_negative_vid():
    s = parse1('INSERT VERTEX player(name) VALUES uuid("x"):("a"), -7:("b")')
    assert s.rows[0][0].to_string() == 'uuid("x")'
    assert s.rows[1][0].value == -7


def test_delete():
    s = parse1("DELETE VERTEX 1, 2")
    assert isinstance(s, ast.DeleteVerticesSentence)
    s = parse1("DELETE EDGE like 1->2@0, 3->4")
    assert isinstance(s, ast.DeleteEdgesSentence)


def test_update_upsert():
    s = parse1('UPDATE VERTEX 100 SET age = age + 1 WHEN age > 10 YIELD age')
    assert isinstance(s, ast.UpdateVertexSentence)
    assert not s.insertable and s.when is not None
    s = parse1('UPSERT EDGE 100 -> 101 OF like SET likeness = 80.0')
    assert isinstance(s, ast.UpdateEdgeSentence)
    assert s.insertable and s.edge == "like"


# --- admin -----------------------------------------------------------------

def test_show_and_use():
    assert parse1("SHOW SPACES").what == ast.ShowKind.SPACES
    assert parse1("SHOW HOSTS").what == ast.ShowKind.HOSTS
    assert parse1("USE nba").space == "nba"
    assert parse1("SHOW TAGS").what == ast.ShowKind.TAGS


def test_configs():
    s = parse1("SHOW CONFIGS GRAPH")
    assert s.action == "SHOW" and s.module == "GRAPH"
    s = parse1("GET CONFIGS STORAGE:foo_bar")
    assert s.action == "GET" and s.name == "foo_bar"
    s = parse1('UPDATE CONFIGS STORAGE:kv_engine_options = "{}"')
    assert s.action == "SET" and s.module == "STORAGE"
    assert s.name == "kv_engine_options"
    # round-trips through to_string (the UPDATE CONFIGS print form)
    assert parse1(s.to_string()).to_string() == s.to_string()
    s = parse1("UPDATE CONFIGS slow_op_threshold_ms = 10")
    assert s.action == "SET" and s.module is None and s.value is not None


def test_balance():
    assert parse1("BALANCE DATA").sub == "DATA"
    assert parse1("BALANCE LEADER").sub == "LEADER"
    assert parse1("BALANCE DATA 123").plan_id == 123
    s = parse1('BALANCE DATA REMOVE "192.168.0.1":44500')
    assert s.remove_hosts == ["192.168.0.1:44500"]


def test_users():
    s = parse1('CREATE USER alice WITH PASSWORD "secret"')
    assert isinstance(s, ast.CreateUserSentence)
    s = parse1('GRANT ROLE ADMIN ON nba TO alice')
    assert s.role == "ADMIN" and s.space == "nba" and s.user == "alice"
    s = parse1('REVOKE ROLE GUEST ON nba FROM bob')
    assert isinstance(s, ast.RevokeSentence)
    s = parse1('CHANGE PASSWORD alice FROM "old" TO "new"')
    assert s.old_password == "old" and s.new_password == "new"


# --- errors + robustness ---------------------------------------------------

@pytest.mark.parametrize("bad", [
    "",
    "GO OVER like",            # missing FROM
    "GO FROM 1",               # missing OVER
    "CREATE",
    "INSERT VERTEX VALUES 1:(2)",
    "FFFFF 1",
    "YIELD",
    "GO FROM 1 OVER like WHERE",
    'INSERT VERTEX p(a) VALUES 1:("unterminated)',
])
def test_syntax_errors(bad):
    with pytest.raises(ParseError):
        parse(bad)


def test_case_insensitive_keywords():
    s = parse1("go from 1 over like yield like._dst")
    assert isinstance(s, ast.GoSentence)


def test_comments_and_whitespace():
    s = parse("GO FROM 1 OVER like # trailing comment\n; // another\nSHOW SPACES")
    assert len(s.sentences) == 2


def test_double_minus_is_not_a_comment():
    s = parse1("YIELD 1--2 AS x")
    from nebula_tpu.filter.expressions import ExpressionContext
    assert s.yield_.columns[0].expr.eval(ExpressionContext()) == 3


def test_scientific_notation():
    s = parse1("YIELD 1e3 AS x, 2.5e-2 AS y")
    assert s.yield_.columns[0].expr.value == 1000.0
    assert s.yield_.columns[1].expr.value == 0.025


def test_power_precedence():
    from nebula_tpu.filter.expressions import ExpressionContext
    ctx = ExpressionContext()
    assert parse1("YIELD 2*3^2 AS x").yield_.columns[0].expr.eval(ctx) == 18
    assert parse1("YIELD 2^3^2 AS x").yield_.columns[0].expr.eval(ctx) == 512
    assert parse1("YIELD 0-2^2 AS x").yield_.columns[0].expr.eval(ctx) == -4


def test_show_roles():
    s = parse1("SHOW ROLES IN nba")
    assert s.what == ast.ShowKind.ROLES and s.arg == "nba"


def test_to_string_roundtrip():
    for q in [
        "GO 2 STEPS FROM 1 OVER like WHERE like.likeness > 90 YIELD like._dst AS id",
        'CREATE TAG player(name STRING, age INT)',
        "INSERT EDGE like(likeness) VALUES 1 -> 2@3:(90.0)",
        "FIND SHORTEST PATH FROM 1 TO 2 OVER like UPTO 4 STEPS",
    ]:
        s1 = parse1(q)
        s2 = parse1(s1.to_string())
        assert s2.to_string() == s1.to_string()


def test_backticked_identifiers():
    s = parse1("CREATE TAG `order`(`limit` int)")
    assert s.name == "order"
    assert s.columns[0].name == "limit"
