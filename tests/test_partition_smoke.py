"""Tier-1-safe partition & gray-failure smoke: `bench.py --partition
--trim` in a SUBPROCESS on XLA:CPU — metad + 3 raft-replicated
storaged + a TPU-engine graphd, with the network nemesis
(common/faults.py link rules in the live transport) driving a
symmetric split of the leader-heaviest storaged, a raft-isolated
follower whose data plane stays open, a gray (slow-not-dead) node, and
a flapping link, all under closed-loop reader traffic and
durability-ledger writers. The artifact must prove: zero acked-write
loss, zero non-retryable client errors, zero replica divergence with
the consistency observatory armed the whole run, follower reads never
served staler than the declared bound (a fenced follower DECLINES —
fence rejections observed while raft-isolated), hedged reads winning
around the gray node with its p99 inside the declared factor of
baseline, and full post-heal convergence (ISSUE 18;
docs/manual/9-robustness.md, docs/manual/12-replication.md)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def partition_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("partition") / "PARTITION_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_PARTITION_SEED"] = "23"
    env["BENCH_PARTITION_OUT"] = str(out)
    # the lock-order witness stays armed through every nemesis phase:
    # injected partitions must not surface a retry loop sleeping under
    # a serve-path lock (the bench gates on the report)
    env["NEBULA_TPU_LOCK_WITNESS"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--partition", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_partition_gates_green(partition_smoke):
    assert partition_smoke["ok"] is True


def test_partition_no_acked_write_lost_no_client_errors(partition_smoke):
    led = partition_smoke["ledger"]
    assert led["missing"] == 0 and led["missing_samples"] == []
    assert led["acked"] > 0          # the ledger actually wrote
    assert led["errors"] == 0        # writers saw no non-retryable code
    cl = partition_smoke["client"]
    assert cl["read_error_count"] == 0 and cl["read_errors"] == []


def test_partition_staleness_bound_held_and_fence_declined(
        partition_smoke):
    fr = partition_smoke["follower_reads"]
    assert fr["staleness_bounded"] is True
    assert fr["max_served_staleness_ms"] <= \
        fr["bound_ms"] + fr["shard_slack_ms"]
    # the raft-isolated follower REFUSED to vouch rather than serving
    # past the bound — the decline is the proof it cannot lie
    assert fr["fence_rejections_while_fenced"] > 0


def test_partition_gray_node_hedged_around(partition_smoke):
    gs = partition_smoke["gray_slo"]
    assert gs["hedge_wins_in_phase"] > 0
    assert gs["gray_p99_ms"] <= \
        gs["declared_factor"] * gs["baseline_p99_ms_floored"]


def test_partition_observatory_convergence(partition_smoke):
    c = partition_smoke["consistency"]
    assert c["divergence"] == 0 and c["divergent_rows"] == []
    assert c["shadow"]["sampled"] > 0
    assert c["shadow"]["mismatches"] == 0
    conv = partition_smoke["convergence"]
    assert conv["committed_ids_converged"] is True
    assert conv["identity"] is True and conv["device_served"] is True
    # every phase carried reader traffic — no phase starved out
    for ph, st in partition_smoke["phases"].items():
        assert st["n"] > 0, (ph, st)
    lw = partition_smoke["lock_witness"]
    assert lw["cycle"] is None and lw["blocking"] == []
