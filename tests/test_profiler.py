"""Continuous profiling observatory (common/profiler.py): the
sampling profiler's role/stack aggregation + trace join, the declared
overhead bound at 19 Hz (ISSUE 13 acceptance), the profile_hz=0 fast
path (zero sampler thread, byte-identical /metrics exposition), the
always-on lock-contention layer, GC pause tracking, the XLA compile
table and the /profile endpoint surface."""
import gc
import threading
import time

import pytest

from nebula_tpu.common import profiler as prof
from nebula_tpu.common.stats import StatsManager
from nebula_tpu.common.stats import stats as global_stats


def _busy_threads(n=3, seconds=0.5, name="busyrole"):
    stop = time.monotonic() + seconds

    def work():
        while time.monotonic() < stop:
            sum(i * i for i in range(500))

    ts = [threading.Thread(target=work, name=f"{name}-{i}", daemon=True)
          for i in range(n)]
    for t in ts:
        t.start()
    return ts


# ------------------------------------------------------------- sampler

def test_thread_role_normalization():
    assert prof.thread_role("raft-repl-1-3-127.0.0.1:5001") == \
        "raft-repl-N-N-N.N.N.N:N"
    assert prof.thread_role("busy-7") == "busy-N"
    assert prof.thread_role("MainThread") == "MainThread"
    assert prof.thread_role("") == "unnamed"


def test_sampler_aggregates_roles_windows_and_collapsed():
    p = prof.SamplingProfiler()
    p.ensure(hz=97)
    ts = _busy_threads(3, 0.5)
    time.sleep(0.4)
    top = p.top(window=60, n=10)
    for t in ts:
        t.join()
    assert top["samples"] > 5
    assert "busyrole-N" in top["threads"]          # digit-normalized role
    assert top["frames"], top
    # shares are a partition of sampled wall time
    assert 0 < sum(f["share"] for f in top["frames"]) <= 1.01
    # role filter narrows to the one role
    only = p.top(window=60, role="busyrole-N")
    assert set(only["threads"]) == {"busyrole-N"}
    # collapsed output is flamegraph.pl shaped: "role;f1;f2 count"
    lines = [ln for ln in p.collapsed(window=600).splitlines() if ln]
    assert lines
    for ln in lines:
        stack, _, count = ln.rpartition(" ")
        assert stack and int(count) > 0
    # lifetime view covers at least the window view
    assert p.top(window=None)["samples"] >= top["samples"]
    p.set_hz(0)


def test_sampler_overhead_under_declared_budget_at_19hz():
    """ISSUE 13 acceptance seed: at the default 19 Hz, under a busy
    multi-thread burst, the sampler's OWN measured self-time stays
    under SAMPLER_OVERHEAD_BUDGET of wall time."""
    p = prof.SamplingProfiler()
    p.ensure(hz=19)
    ts = _busy_threads(4, 1.1)
    time.sleep(1.0)
    for t in ts:
        t.join()
    assert p.ticks > 5, "sampler never ran"
    overhead = p.overhead()
    assert overhead < prof.SAMPLER_OVERHEAD_BUDGET, (
        f"sampler overhead {overhead:.4f} over declared budget "
        f"{prof.SAMPLER_OVERHEAD_BUDGET}")
    st = p.state()
    assert st["overhead_budget"] == prof.SAMPLER_OVERHEAD_BUDGET
    p.set_hz(0)


def test_profile_hz_zero_no_thread_and_byte_identical_metrics():
    """The fast path: profile_hz=0 creates NO sampler thread, and a
    StatsManager serving a workload next to a disarmed profiler emits
    a byte-identical OpenMetrics exposition to one that never saw a
    profiler at all."""
    before = sum(1 for t in threading.enumerate()
                 if t.name == "profiler-sampler")
    p = prof.SamplingProfiler()
    p.ensure(hz=0)
    assert not p.thread_alive()
    after = sum(1 for t in threading.enumerate()
                if t.name == "profiler-sampler")
    assert after == before, "hz=0 must not spawn a sampler thread"
    assert p.samples == 0 and p.ticks == 0

    clock = [1000.0]
    sm_plain = StatsManager(clock=lambda: clock[0])
    sm_prof = StatsManager(clock=lambda: clock[0])
    disarmed = prof.SamplingProfiler(clock=lambda: clock[0],
                                     stats=sm_prof)
    disarmed.ensure(hz=0)
    for sm in (sm_plain, sm_prof):
        sm.add_value("graph.query_latency_us", 1234, kind="histogram")
        sm.add_value("rpc.reconnects", kind="counter")
        sm.add_value("op_us", 55, kind="timing")
    a = "\n".join(sm_plain.prometheus_lines())
    b = "\n".join(sm_prof.prometheus_lines())
    assert a == b


def test_sampler_tags_samples_with_trace_context():
    """The trace join: a thread running inside a sampled trace is
    mirrored (common/tracing.py note_trace), and the sampler tags its
    samples with that trace id."""
    from nebula_tpu.common import tracing
    p = prof.SamplingProfiler()
    p.ensure(hz=151)
    seen = {}

    def traced_work():
        h = tracing.tracer.begin("profiled-query", force=True)
        seen["trace_id"] = h.trace_id
        stop = time.monotonic() + 0.4
        while time.monotonic() < stop:
            sum(i for i in range(500))
        h.finish()

    t = threading.Thread(target=traced_work, name="traced-worker",
                         daemon=True)
    t.start()
    time.sleep(0.3)
    t.join()
    p.set_hz(0)
    tagged = p.tagged_samples(256)
    assert tagged, "no trace-tagged samples captured"
    assert any(s["trace_id"] == seen["trace_id"] for s in tagged)
    assert all(s["role"] == "traced-worker" for s in tagged
               if s["trace_id"] == seen["trace_id"])


def test_capture_is_private_and_bounded():
    p = prof.SamplingProfiler()
    ts = _busy_threads(2, 0.4)
    cap = p.capture(0.2, hz=200)
    for t in ts:
        t.join()
    assert cap["samples"] > 0
    assert cap["frames"]
    assert "collapsed" in cap
    # the always-on aggregation stayed untouched (sampler never armed)
    assert p.samples == 0


# ------------------------------------------------------- lock profiler

def test_profiled_lock_contention_blame_and_histogram():
    lk = prof.profiled_lock("t_contend")

    def holder():
        with lk:
            time.sleep(0.08)

    h = threading.Thread(target=holder, name="blame-holder-1",
                         daemon=True)
    h.start()
    time.sleep(0.02)
    t0 = time.perf_counter()
    with lk:
        waited = time.perf_counter() - t0
    h.join()
    assert waited > 0.02
    site = [s for s in prof.lock_table(50) if s["name"] == "t_contend"]
    assert site, prof.lock_table(50)
    s = site[0]
    assert s["contended"] >= 1
    assert s["acquires"] >= 2
    assert s["wait_us_total"] >= 20000
    assert s["last_holder"] == "blame-holder-N"
    assert s["blame"].get("blame-holder-N", 0) >= 1
    # the native histogram family landed (exemplar-capable, scrapes
    # as nebula_lock_wait_us_t_contend)
    assert "lock.wait_us.t_contend" in global_stats.histogram_names()
    snap = global_stats.histogram_snapshot("lock.wait_us.t_contend")
    assert snap["count"] >= 1


def test_profiled_condition_reacquire_counts_as_contention():
    """Condition over a profiled lock: the waiter's re-acquire after
    notify (while the notifier still holds the lock) is timed by
    _acquire_restore and lands on the site."""
    cv = threading.Condition(prof.profiled_rlock("t_cv"))
    ready = threading.Event()

    def waiter():
        with cv:
            ready.set()
            cv.wait(timeout=5)

    t = threading.Thread(target=waiter, name="cv-waiter", daemon=True)
    t.start()
    assert ready.wait(2)
    with cv:
        cv.notify_all()
        # hold the lock past the notify: the woken waiter must queue
        # on the re-acquire
        time.sleep(0.05)
    t.join(2)
    site = [s for s in prof.lock_table(50) if s["name"] == "t_cv"][0]
    assert site["contended"] >= 1
    assert site["wait_us_max"] >= 10000


def test_profiled_lock_uncontended_records_nothing():
    lk = prof.profiled_lock("t_quiet")
    for _ in range(50):
        with lk:
            pass
    site = [s for s in prof.lock_table(50) if s["name"] == "t_quiet"][0]
    assert site["acquires"] == 50
    assert site["contended"] == 0
    assert "lock.wait_us.t_quiet" not in global_stats.histogram_names()


def test_profiled_lock_non_blocking_and_locked():
    lk = prof.profiled_lock("t_nb")
    assert lk.acquire()
    assert not lk.acquire(blocking=False)   # same-site Lock, held
    assert lk.locked()
    lk.release()
    assert not lk.locked()


# -------------------------------------------------------- gc profiler

def test_gc_profiler_records_pauses_and_flight_event():
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.common.flight import recorder
    sm = StatsManager()
    g = prof.GcProfiler(stats=sm)
    g.install()
    prev = graph_flags.get("gc_pause_flight_ms")
    graph_flags.set("gc_pause_flight_ms", 0.0)   # every pause = event
    n0 = sum(1 for e in recorder.describe(limit=10000)["events"]
             if e["kind"] == "gc_pause")
    try:
        gc.collect()
    finally:
        graph_flags.set("gc_pause_flight_ms", prev)
        g.uninstall()
    t = g.table()
    assert sum(t["collections"]) >= 1
    assert t["pause_us_total"] >= 0
    assert "graph.gc.pause_us" in sm.histogram_names()
    n1 = sum(1 for e in recorder.describe(limit=10000)["events"]
             if e["kind"] == "gc_pause")
    assert n1 > n0, "gc_pause flight event not recorded"
    assert any(v >= 1 for k, v in g.gauges().items()
               if k.startswith("graph.gc.collections."))


# ------------------------------------------------------ compile table

def test_compile_table_times_first_call_only():
    sm = StatsManager()
    table = prof.CompileTable(stats=sm)
    calls = []

    def fake_program(x):
        calls.append(x)
        if len(calls) == 1:
            time.sleep(0.01)     # the "compile" happens on first call
        return x * 2

    fake_program.custom_attr = "passthrough"
    wrapped = table.timed_first_call(fake_program, "sig-A")
    assert wrapped(3) == 6
    assert wrapped(4) == 8
    rows = table.table()
    assert len(rows) == 1
    assert rows[0]["signature"] == "sig-A"
    assert rows[0]["compiles"] == 1          # only the first call
    assert rows[0]["total_us"] >= 5000
    assert table.totals()["signatures"] == 1
    assert "tpu_engine.compile_us" in sm.histogram_names()
    # jit-callable attribute passthrough (the registry exposes
    # _cache_size etc. through the wrapper)
    assert wrapped.custom_attr == "passthrough"


# ------------------------------------------------- ctx mirror + verbs

def test_ledger_begin_set_verb_mirrors_and_restores():
    from nebula_tpu.common import ledger
    tid = threading.get_ident()
    led, tok = ledger.begin()
    assert led is not None
    assert prof._thread_verb.get(tid) is None
    ledger.set_verb(led, "GO")
    assert prof._thread_verb.get(tid) == "GO"
    assert led.verb == "GO"
    ledger.end(tok)
    assert prof._thread_verb.get(tid) is None


def test_tracing_use_repoints_thread_trace_mirror():
    from nebula_tpu.common import tracing
    tid = threading.get_ident()
    h = tracing.tracer.begin("outer", force=True)
    assert prof._thread_trace.get(tid) == h.trace_id
    with tracing.tracer.use(None):
        assert prof._thread_trace.get(tid) is None
    assert prof._thread_trace.get(tid) == h.trace_id
    h.finish()
    assert prof._thread_trace.get(tid) is None


def test_flight_bundles_embed_profile_collector():
    """ensure_started registers the `profile` flight collector: every
    bundle captured afterwards embeds the anomaly window's hot frames,
    trace-tagged samples and lock/GC/compile tables."""
    from nebula_tpu.common.flight import recorder
    prof.ensure_started()
    assert "profile" in recorder._collectors
    blk = prof.flight_block()
    assert set(blk) >= {"state", "top", "tagged_samples", "locks",
                        "gc", "compiles"}
    assert "frames" in blk["top"]


# ---------------------------------------------------------- endpoint

def test_profile_endpoint_surface():
    code, body = prof.profile_endpoint({"locks": "1"}, b"")
    assert code == 200 and "locks" in body
    code, body = prof.profile_endpoint({"compiles": "1"}, b"")
    assert code == 200 and "compiles" in body and "totals" in body
    code, body = prof.profile_endpoint({}, b"")
    assert code == 200
    for key in ("state", "frames", "threads", "gc", "locks",
                "compiles"):
        assert key in body
    code, body = prof.profile_endpoint({"window": "7"}, b"")
    assert code == 400
    code, body = prof.profile_endpoint({"seconds": "nope"}, b"")
    assert code == 400
    code, body = prof.profile_endpoint({"top": "xx"}, b"")
    assert code == 400
    code, body = prof.profile_endpoint({"format": "collapsed"}, b"")
    assert code == 200 and isinstance(body, bytes)
    code, body = prof.profile_endpoint(
        {"seconds": "0.05", "hz": "50"}, b"")
    assert code == 200 and body["samples"] >= 0 and "frames" in body
