"""Multi-tenant QoS tests (common/qos.py, graph admission gate,
dispatcher priority lanes + load shedding, StorageClient deadline
budget; docs/manual/14-qos.md).

The contract under test, end to end: an over-budget or shed query gets
a typed, RETRYABLE ``E_OVERLOAD`` with a retry-after hint — never a
hang, never a generic failure, never a silent CPU fallback — and every
denial/shed is visible (trace-root tags ``admission_denied`` /
``shed:*`` + Prometheus counters), the same observability contract the
degradation ladder keeps for its tags (PR 4's soak --chaos)."""
import threading
import time

import pytest

from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common import qos
from nebula_tpu.common.flags import graph_flags
from nebula_tpu.common.qos import (LANE_BULK, LANE_INTERACTIVE,
                                   AdmissionController, OverloadShed,
                                   TokenBucket, admission)
from nebula_tpu.common.stats import stats as global_stats
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture(autouse=True)
def _clean_qos():
    """The controller and the QoS flags are process-global: never leak
    an armed plan or a shed watermark into unrelated tests."""
    admission.reset()
    for f, v in (("qos_plan", ""), ("qos_shed_queue_depth", 0),
                 ("qos_shed_wait_p95_ms", 0)):
        graph_flags.set(f, v)
    yield
    admission.reset()
    for f, v in (("qos_plan", ""), ("qos_shed_queue_depth", 0),
                 ("qos_shed_wait_p95_ms", 0)):
        graph_flags.set(f, v)


# ---------------------------------------------------------------------------
# token bucket + controller unit tests
# ---------------------------------------------------------------------------

def test_token_bucket_burst_then_refill():
    now = [0.0]
    tb = TokenBucket(rate=10, burst=2, clock=lambda: now[0])
    assert tb.try_acquire() == (True, 0.0)
    assert tb.try_acquire() == (True, 0.0)
    ok, retry = tb.try_acquire()
    assert not ok and retry == pytest.approx(0.1)   # 1 token @ 10/s
    now[0] += 0.1                                   # refill exactly one
    assert tb.try_acquire()[0]
    assert not tb.try_acquire()[0]


def test_token_bucket_zero_rate_is_deny_all():
    """rate=0 = the emergency tenant block: denies OUTRIGHT, never a
    one-shot burst-token leak per plan swap (the doc's 'rate=0 denies
    every data statement' is literal)."""
    tb = TokenBucket(rate=0, burst=5)
    ok, retry = tb.try_acquire()
    assert not ok and retry == qos.MAX_RETRY_AFTER_MS / 1e3


def test_admission_plan_parse_and_isolation():
    ctl = AdmissionController()
    ctl.set_plan("noisy:rate=0,burst=1,lane=bulk;*:rate=1000")
    ok, retry_ms, lane = ctl.admit("noisy")
    assert not ok and lane == LANE_BULK     # deny-all, lane intact
    ok, retry_ms, _ = ctl.admit("noisy")
    assert not ok and retry_ms >= qos.MIN_RETRY_AFTER_MS
    # other spaces ride the default policy, unaffected by the abuser
    for _ in range(50):
        assert ctl.admit("quiet")[0]
    d = ctl.describe()
    assert d["spaces"]["noisy"]["denied"] >= 1
    assert d["spaces"]["quiet"]["denied"] == 0
    assert d["spaces"]["quiet"]["admitted"] == 50


def test_admission_unnamed_space_unlimited_without_default():
    ctl = AdmissionController()
    ctl.set_plan("noisy:rate=1")
    for _ in range(100):
        assert ctl.admit("anything")[0]
    assert not ctl.armed() or ctl.describe()["spaces"]["noisy"] is not None


def test_admission_bad_plans_rejected_previous_kept():
    ctl = AdmissionController()
    ctl.set_plan("a:rate=1")
    for bad in ("a:burst=2", "a:rate=x", "a:nope=1", ":rate=1",
                "a:lane=warp"):
        with pytest.raises(ValueError):
            ctl.set_plan(bad)
    assert ctl.describe()["plan"] == "a:rate=1"     # kept
    ctl.set_plan("")
    assert not ctl.armed()


def test_qos_plan_flag_feeds_controller():
    graph_flags.set("qos_plan", "flagspace:rate=7,burst=9")
    d = admission.describe()
    assert d["spaces"]["flagspace"]["policy"] == {"rate": 7.0,
                                                  "burst": 9.0}
    graph_flags.set("qos_plan", "not a plan !!!")   # bad hot-set: kept
    assert admission.describe()["spaces"]["flagspace"][
        "policy"]["rate"] == 7.0
    graph_flags.set("qos_plan", "")
    assert not admission.armed()


# ---------------------------------------------------------------------------
# graph-layer admission gate (e2e through a real cluster)
# ---------------------------------------------------------------------------

def _mini_cluster(space="qz", parts=2, v=60, e=240, seed=3):
    import numpy as np
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    conn.must(f"CREATE SPACE {space}(partition_num={parts})")
    conn.must(f"USE {space}")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(w int)")
    conn.must("INSERT VERTEX person(age) VALUES " + ", ".join(
        f"{i}:({i % 70})" for i in range(v)))
    rng = np.random.default_rng(seed)
    srcs = rng.integers(0, v, e)
    dsts = rng.integers(0, v, e)
    for i in range(0, e, 200):
        conn.must("INSERT EDGE knows(w) VALUES " + ", ".join(
            f"{int(s)} -> {int(d)}@{j}:({int((s + d) % 50)})"
            for j, (s, d) in enumerate(zip(srcs[i:i + 200],
                                           dsts[i:i + 200]), start=i)))
    sid = cluster.meta.get_space(space).value().space_id
    return cluster, conn, tpu, sid


@pytest.fixture()
def mini():
    return _mini_cluster()


def test_admission_denial_is_typed_retryable_and_observable(mini):
    """Throttled queries: E_OVERLOAD + retry-after hint + trace-root
    `admission_denied` tag + Prometheus counter — and recovery after
    the hinted wait (the RETRYABLE half of the contract)."""
    from nebula_tpu.common.tracing import tracer
    cluster, conn, tpu, sid = mini
    q = "GO FROM 1 OVER knows YIELD knows._dst"
    conn.must(q)
    graph_flags.set("trace_sample_rate", 1.0)
    graph_flags.set("qos_plan", "qz:rate=50,burst=1")
    c0 = global_stats.lifetime_total("graph.qos.denied.qz")
    try:
        r1 = conn.execute(q)             # the burst token: admitted
        assert r1.ok(), r1.error_msg
        r2 = conn.execute(q)             # bucket empty: typed denial
        assert r2.code == ErrorCode.E_OVERLOAD
        assert "retry" in r2.error_msg and "E_OVERLOAD" in r2.error_msg
        hint = (r2.profile or {}).get("retry_after_ms")
        assert isinstance(hint, int) and hint >= qos.MIN_RETRY_AFTER_MS
        # retryable: after the hinted wait the query is admitted again
        time.sleep(min(hint, 1000) / 1e3 + 0.05)
        r3 = conn.execute(q)
        assert r3.ok(), r3.error_msg
    finally:
        graph_flags.set("trace_sample_rate", 0.0)
        graph_flags.set("qos_plan", "")
    assert global_stats.lifetime_total("graph.qos.denied.qz") > c0
    denied_traces = [t for t in tracer.ring.snapshot()
                     if t.get("tags", {}).get("admission_denied") == "qz"]
    assert denied_traces, "denial did not tag its trace root"
    # admin/session statements stay exempt: a throttled tenant can
    # still navigate
    graph_flags.set("qos_plan", "qz:rate=0")    # deny-all block
    try:
        assert conn.execute("SHOW SPACES").ok()
        assert conn.execute("USE qz").ok()
        assert conn.execute(q).code == ErrorCode.E_OVERLOAD
    finally:
        graph_flags.set("qos_plan", "")


def test_admission_gates_post_use_space_and_charges_per_sentence(mini):
    """Two bypass regressions (found in review): (1) `USE abuser;
    GO ...` smuggled in ONE request must gate against the POST-USE
    space — the gate is per sentence, not per request; (2) a compound
    of N gated sentences charges N tokens, not one."""
    cluster, conn, tpu, sid = mini
    graph_flags.set("qos_plan", "qz:rate=0")
    try:
        c2 = cluster.connect()           # fresh session: no space yet
        r = c2.execute("USE qz; GO FROM 1 OVER knows YIELD knows._dst")
        assert r.code == ErrorCode.E_OVERLOAD, (r.code, r.error_msg)
        assert "qz" in r.error_msg
    finally:
        graph_flags.set("qos_plan", "")
    graph_flags.set("qos_plan", "qz:rate=1,burst=2")
    a0 = admission.describe()["spaces"].get("qz", {}).get("admitted", 0)
    try:
        r = conn.execute("GO FROM 1 OVER knows; GO FROM 2 OVER knows; "
                         "GO FROM 3 OVER knows")
        # the 3rd sentence exceeds the 2-token burst mid-sequence
        assert r.code == ErrorCode.E_OVERLOAD, (r.code, r.error_msg)
        assert admission.describe()["spaces"]["qz"]["admitted"] \
            - a0 == 2
    finally:
        graph_flags.set("qos_plan", "")


def test_admission_bad_flag_hot_set_is_counted(mini):
    """A malformed qos_plan hot-set through the flag path keeps the
    previous plan AND leaves evidence (counter + log) — the flag value
    and controller state must not diverge silently."""
    b0 = global_stats.lifetime_total("graph.qos.bad_plan")
    graph_flags.set("qos_plan", "ok:rate=5")
    graph_flags.set("qos_plan", "broken:rate=")
    assert admission.describe()["plan"] == "ok:rate=5"
    assert global_stats.lifetime_total("graph.qos.bad_plan") > b0
    graph_flags.set("qos_plan", "")


def test_admission_prometheus_lines_exposed(mini):
    cluster, conn, tpu, sid = mini
    graph_flags.set("qos_plan", "qz:rate=0,burst=1")
    try:
        conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
        conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
    finally:
        graph_flags.set("qos_plan", "")
    lines = "\n".join(global_stats.prometheus_lines())
    assert "nebula_graph_qos_denied_qz_total" in lines
    assert "nebula_graph_qos_admission_denied_total" in lines


# ---------------------------------------------------------------------------
# lane classification + overrides
# ---------------------------------------------------------------------------

def _classify(text):
    from nebula_tpu.graph.engine import classify_lane
    from nebula_tpu.parser import GQLParser
    return classify_lane(GQLParser().parse(text))


def test_statement_shape_classification():
    assert _classify("GO FROM 1 OVER knows") == LANE_INTERACTIVE
    assert _classify("GO 2 STEPS FROM 1 OVER knows") == LANE_INTERACTIVE
    assert _classify("GO 3 STEPS FROM 1 OVER knows") == LANE_BULK
    # a pipe rides its scan's weight
    assert _classify("GO 3 STEPS FROM 1 OVER knows YIELD knows.w AS w"
                     " | YIELD COUNT(*) AS n") == LANE_BULK
    assert _classify("GO FROM 1 OVER knows YIELD knows.w AS w"
                     " | YIELD COUNT(*) AS n") == LANE_INTERACTIVE
    # wide multi-start GO classifies bulk past qos_bulk_starts
    wide = ", ".join(str(i) for i in range(40))
    assert _classify(f"GO FROM {wide} OVER knows") == LANE_BULK
    assert _classify("FIND ALL PATH FROM 1 TO 2 OVER knows "
                     "UPTO 5 STEPS") == LANE_BULK
    # the threshold is a MUTABLE flag
    graph_flags.set("qos_bulk_steps", 2)
    try:
        assert _classify("GO 2 STEPS FROM 1 OVER knows") == LANE_BULK
    finally:
        graph_flags.set("qos_bulk_steps", 3)


def test_session_and_plan_lane_overrides(mini):
    """Pecking order: session pin > space-plan lane > statement
    shape. Observed at the engine seam via ctx.qos_lane."""
    cluster, conn, tpu, sid = mini
    seen = []
    orig = tpu.execute_go

    def spy(ctx, *a, **kw):
        seen.append(getattr(ctx, "qos_lane", None))
        return orig(ctx, *a, **kw)

    tpu.execute_go = spy
    q = "GO FROM 1 OVER knows YIELD knows._dst"
    try:
        conn.must(q)
        assert seen[-1] == LANE_INTERACTIVE
        # space-plan lane override
        graph_flags.set("qos_plan", "qz:rate=1000,lane=bulk")
        conn.must(q)
        assert seen[-1] == LANE_BULK
        # session pin beats the plan
        sess = cluster.service.sessions.find(conn.session_id).value()
        sess.qos_lane = LANE_INTERACTIVE
        conn.must(q)
        assert seen[-1] == LANE_INTERACTIVE
        sess.qos_lane = None
    finally:
        tpu.execute_go = orig
        graph_flags.set("qos_plan", "")


# ---------------------------------------------------------------------------
# weighted-fair priority lanes at the dispatcher
# ---------------------------------------------------------------------------

def test_bulk_cannot_monopolize_concurrent_rounds(mini):
    """4 distinct-key bulk groups + paced rounds: bulk in-flight
    rounds never exceed bulk_max_rounds, and an interactive query
    arriving mid-burst completes without waiting for the whole bulk
    backlog."""
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0          # pin dense: dispatcher path
    # warm every query shape (compiles off the measurement)
    bulk_qs = [f"GO {s} STEPS FROM {v} OVER knows YIELD knows._dst"
               for s, v in ((3, 1), (3, 2), (4, 3), (4, 4))]
    inter_q = "GO FROM 5 OVER knows YIELD knows._dst"
    for q in bulk_qs + [inter_q]:
        conn.must(q)

    observed = []
    orig = tpu._serve_batch

    def paced(batch, ex):
        with tpu._disp_cv:
            observed.append(dict(tpu._lane_rounds))
        time.sleep(0.05)
        orig(batch, ex)

    tpu._serve_batch = paced
    errs = []
    done_at = {}

    def run(q, name):
        try:
            c = cluster.connect()
            c.must("USE qz")
            for _ in range(3):
                c.must(q)
            done_at[name] = time.monotonic()
        except Exception as ex:   # noqa: BLE001 — recorded, fails test
            errs.append(repr(ex))

    # distinct steps per query -> 4 distinct group keys, all bulk
    try:
        t0 = time.monotonic()
        ths = [threading.Thread(target=run, args=(q, f"bulk{i}"))
               for i, q in enumerate(bulk_qs)]
        for t in ths:
            t.start()
        time.sleep(0.02)                # bulk burst in flight first
        ti = threading.Thread(target=run, args=(inter_q, "inter"))
        ti.start()
        ti.join(timeout=120)
        for t in ths:
            t.join(timeout=120)
    finally:
        tpu._serve_batch = orig
    assert not errs, errs
    assert observed, "no dispatcher rounds observed"
    assert max(o[LANE_BULK] for o in observed) <= tpu.bulk_max_rounds
    assert tpu.stats["lane_rounds_bulk"] > 0
    assert tpu.stats["lane_rounds_interactive"] > 0
    # the interactive session never queued behind the full bulk drain
    assert done_at["inter"] - t0 <= max(done_at[f"bulk{i}"]
                                        for i in range(4)) - t0 + 0.5


def test_resolved_wide_starts_upgrade_to_bulk(mini):
    """Width-abuse regression (found in review): a piped GO whose
    start set resolves wide at runtime parses with ZERO literal vids,
    so the parse-time classifier says interactive — the dispatcher
    must re-check the RESOLVED width and upgrade to bulk (explicit
    session/plan pins still win)."""
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0          # pin dense: dispatcher path
    graph_flags.set("qos_bulk_starts", 4)
    seen = []
    orig = tpu._serve_batch

    def spy(batch, ex):
        seen.extend((r.lane, len(r.starts)) for r in batch)
        orig(batch, ex)

    tpu._serve_batch = spy
    try:
        conn.must("GO FROM 1 OVER knows YIELD knows._dst AS id | "
                  "GO FROM $-.id OVER knows YIELD knows._dst")
    finally:
        tpu._serve_batch = orig
        graph_flags.set("qos_bulk_starts", 32)
    wide = [(lane, n) for lane, n in seen if n >= 4]
    assert wide, f"no wide window observed: {seen}"
    assert all(lane == LANE_BULK for lane, n in wide), seen
    # a pinned session is honored verbatim, no upgrade
    sess = cluster.service.sessions.find(conn.session_id).value()
    sess.qos_lane = LANE_INTERACTIVE
    graph_flags.set("qos_bulk_starts", 4)
    seen.clear()
    tpu._serve_batch = spy
    try:
        conn.must("GO FROM 1 OVER knows YIELD knows._dst AS id | "
                  "GO FROM $-.id OVER knows YIELD knows._dst")
    finally:
        tpu._serve_batch = orig
        graph_flags.set("qos_bulk_starts", 32)
        sess.qos_lane = None
    assert all(lane == LANE_INTERACTIVE for lane, _ in seen), seen


# ---------------------------------------------------------------------------
# load shedding at the watermarks
# ---------------------------------------------------------------------------

def test_shed_bulk_first_typed_tagged_and_counted(mini):
    """Seeded wait-p95 over the watermark: the next BULK query sheds
    to a typed E_OVERLOAD (trace-tagged shed:<reason>, counted), while
    an INTERACTIVE query — same watermark, 2x multiplier — still
    serves. Shedding never silently degrades to the CPU pipe."""
    from nebula_tpu.common.tracing import tracer
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    bulk_q = "GO 3 STEPS FROM 1 OVER knows YIELD knows._dst"
    inter_q = "GO FROM 1 OVER knows YIELD knows._dst"
    conn.must(bulk_q)
    conn.must(inter_q)
    # the recent-round window says waits are running at ~150ms p95
    with tpu._disp_cv:
        tpu._wait_samples.extend([150.0] * tpu.WAIT_SAMPLE_WINDOW)
    graph_flags.set("trace_sample_rate", 1.0)
    graph_flags.set("qos_shed_wait_p95_ms", 100)
    d0 = tpu.stats["degraded_serves"]
    s0 = global_stats.lifetime_total("tpu_engine.qos.shed.wait_p95")
    try:
        r = conn.execute(bulk_q)
        assert r.code == ErrorCode.E_OVERLOAD, (r.code, r.error_msg)
        assert "retry" in r.error_msg
        # the machine-readable hint rides the SAME contract as an
        # admission denial (clients read profile.retry_after_ms)
        hint = (r.profile or {}).get("retry_after_ms")
        assert isinstance(hint, int) and hint >= 25, r.profile
        ri = conn.execute(inter_q)       # 150 < 2x100: not shed
        assert ri.ok(), ri.error_msg
    finally:
        graph_flags.set("qos_shed_wait_p95_ms", 0)
        graph_flags.set("trace_sample_rate", 0.0)
    assert tpu.stats["qos_shed"] >= 1
    assert tpu.qos_shed_reasons.get("wait_p95:bulk", 0) >= 1
    assert tpu.qos_shed_by_space.get(sid, 0) >= 1
    assert global_stats.lifetime_total(
        "tpu_engine.qos.shed.wait_p95") > s0
    # shed != degraded: the CPU pipe was NOT used for the shed query
    assert tpu.stats["degraded_serves"] == d0
    shed_traces = [t for t in tracer.ring.snapshot()
                   if "shed" in t.get("tags", {})]
    assert shed_traces and \
        shed_traces[-1]["tags"]["shed"] == "wait_p95:bulk"
    # watermark cleared: bulk serves again (retryable, not sticky)
    with tpu._disp_cv:
        tpu._wait_samples.clear()
    assert conn.execute(bulk_q).ok()


def test_shed_queue_depth_watermark(mini):
    cluster, conn, tpu, sid = mini
    tpu.sparse_edge_budget = 0
    bulk_q = "GO 3 STEPS FROM 2 OVER knows YIELD knows._dst"
    conn.must(bulk_q)
    graph_flags.set("qos_shed_queue_depth", 1)
    orig = tpu._serve_batch

    def paced(batch, ex):
        time.sleep(0.08)
        orig(batch, ex)

    tpu._serve_batch = paced
    codes = []
    lock = threading.Lock()

    def run():
        c = cluster.connect()
        c.must("USE qz")
        r = c.execute(bulk_q)
        with lock:
            codes.append(r.code)

    try:
        ths = [threading.Thread(target=run) for _ in range(8)]
        for t in ths:
            t.start()
            time.sleep(0.01)            # arrivals pile behind the
        for t in ths:                   # paced in-flight round
            t.join(timeout=120)
    finally:
        tpu._serve_batch = orig
        graph_flags.set("qos_shed_queue_depth", 0)
    assert ErrorCode.E_OVERLOAD in codes, codes
    assert all(c in (ErrorCode.SUCCEEDED, ErrorCode.E_OVERLOAD)
               for c in codes), codes
    assert tpu.qos_shed_reasons.get("queue_depth:bulk", 0) >= 1


def test_qos_stats_block_shape(mini):
    cluster, conn, tpu, sid = mini
    q = tpu.qos_stats()
    for key in ("queue_depth", "group_wait_p95_ms", "lane_rounds",
                "lane_rounds_in_flight", "shed", "shed_reasons",
                "shed_by_space", "watermarks", "lane_weights",
                "bulk_max_rounds"):
        assert key in q
    assert set(q["lane_rounds"]) == {LANE_INTERACTIVE, LANE_BULK}


# ---------------------------------------------------------------------------
# deadline budget vs retry loops (ISSUE 8 satellite: _fanout)
# ---------------------------------------------------------------------------

class _OnePartSM:
    def num_parts(self, space_id):
        return 1


class _ElectingForever:
    """Hintless E_LEADER_CHANGED on every call — a stalled election."""

    def __init__(self):
        self.calls = 0

    def get_vertex_props(self, space_id, parts, tag_ids):
        from nebula_tpu.storage.types import PartResult, PropsResponse
        self.calls += 1
        r = PropsResponse()
        for p in parts:
            r.results[p] = PartResult(ErrorCode.E_LEADER_CHANGED, None)
        return r


def test_fanout_deadline_balks_instead_of_retrying_past_it():
    """A stalled election with 150ms of query budget left: the retry
    loop must balk to a typed E_TIMEOUT (deadline_exceeded) within the
    budget's order of magnitude — not burn the full 5-round hintless
    backoff (~1.5s) past the query's own deadline."""
    from nebula_tpu.storage.client import StorageClient
    svc = _ElectingForever()
    client = StorageClient(_OnePartSM(), hosts={"h0": svc, "h1": svc},
                           part_to_host=lambda s, p: "h0")
    b0 = global_stats.lifetime_total(
        "storage_client.fanout_deadline_balk")
    tok = qos.set_query_deadline(time.monotonic() + 0.15)
    t0 = time.monotonic()
    try:
        resp = client.get_vertex_props(1, [1])
    finally:
        qos.clear_query_deadline(tok)
    dt = time.monotonic() - t0
    assert resp.results[1].code == ErrorCode.E_TIMEOUT, resp.results
    assert dt < 1.0, f"retried past the deadline ({dt:.2f}s)"
    assert global_stats.lifetime_total(
        "storage_client.fanout_deadline_balk") > b0


def test_fanout_without_deadline_keeps_full_retry_budget():
    """No deadline armed -> the PR 6 behavior is untouched: the full
    hintless budget runs (it must outlast an election) and the
    exhausted parts surface as E_HOST_NOT_FOUND."""
    from nebula_tpu.storage.client import StorageClient
    svc = _ElectingForever()
    client = StorageClient(_OnePartSM(), hosts={"h0": svc, "h1": svc},
                           part_to_host=lambda s, p: "h0")
    assert qos.deadline_remaining_s() is None
    resp = client.get_vertex_props(1, [1])
    # exhaustion surfaces the last round's verdict, exactly as PR 6
    # left it (a still-electing part stays E_LEADER_CHANGED)
    assert resp.results[1].code == ErrorCode.E_LEADER_CHANGED
    assert svc.calls == 6               # initial + 5 retries


def test_graph_service_arms_deadline_context(mini):
    """GraphService.execute arms the per-query deadline from
    tpu_query_deadline_ms, and clears it afterwards."""
    cluster, conn, tpu, sid = mini
    seen = []
    orig = cluster.service.engine.execute

    def spy(session, text):
        seen.append(qos.deadline_remaining_s())
        return orig(session, text)

    cluster.service.engine.execute = spy
    try:
        graph_flags.set("tpu_query_deadline_ms", 5000)
        conn.must("YIELD 1")
        assert seen[-1] is not None and 0 < seen[-1] <= 5.0
        graph_flags.set("tpu_query_deadline_ms", 0)
        conn.must("YIELD 1")
        assert seen[-1] is None
    finally:
        cluster.service.engine.execute = orig
        graph_flags.set("tpu_query_deadline_ms", 60000)
    assert qos.deadline_remaining_s() is None
