"""Tier-1-safe multi-tenant QoS smoke: `bench.py --tenants --trim` in
a SUBPROCESS on XLA:CPU — one abusive tenant firing bulk scans against
small tenants with the QoS ladder armed (per-space admission, priority
lanes, shed watermarks; docs/manual/14-qos.md). The tier itself FAILS
unless the abuser is throttled with typed E_OVERLOAD only, every small
tenant's p99 holds within the declared factor of its no-abuser
baseline, and TPU-vs-CPU identity is green — the subprocess keeps the
parent's JAX backend state out of the picture, exactly like the chaos
and cluster smoke tiers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tenants_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("tenants") / "TENANTS_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_TENANTS_SEED"] = "13"    # deterministic graphs/load
    env["BENCH_TENANTS_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--tenants", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_tenants_abuser_throttled_not_starved(tenants_smoke):
    ab = tenants_smoke["abuser"]
    assert ab["denied"] > 0, ab          # admission actually bit
    assert ab["overloads"] > 0, ab       # ...and the client saw typed
    assert ab["served"] > 0, ab          # throttled, never starved


def test_tenants_small_p99_holds_and_no_overloads(tenants_smoke):
    for t, rec in tenants_smoke["per_tenant"].items():
        assert rec["p99_within_bound"], (t, rec)
        assert rec["abuse"]["n"] > 0, (t, rec)
    assert tenants_smoke["small_tenant_overloads"] == 0


def test_tenants_only_typed_overload_errors_and_identity(tenants_smoke):
    assert tenants_smoke["client_error_count"] == 0, \
        tenants_smoke["client_errors"]
    assert tenants_smoke["identity"]["mismatches"] == []
    assert tenants_smoke["identity"]["checked"] > 0


def test_tenants_qos_slices_present(tenants_smoke):
    qos = tenants_smoke["qos"]
    spaces = qos["admission"]["spaces"]
    assert "abuser" in spaces and spaces["abuser"]["denied"] > 0
    # per-tenant slices: every small tenant visible, none throttled
    smalls = [s for s in spaces if s.startswith("tenant")]
    assert smalls and all(spaces[s]["denied"] == 0 for s in smalls)
    # the abuser's scans actually rode the bulk lane
    assert qos["dispatcher"]["lane_rounds"]["bulk"] > 0
    assert qos["dispatcher"]["lane_rounds"]["interactive"] > 0
