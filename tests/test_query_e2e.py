"""Full-stack query tests on the NBA sample (parity model: graph/test/
GoTest.cpp, FindPathTest.cpp, YieldTest.cpp, SchemaTest.cpp, DataTest.cpp,
GroupByLimitTest.cpp — golden result-table assertions)."""
import pytest

from nebula_tpu.common.status import ErrorCode
from nba_fixture import load_nba


@pytest.fixture(scope="module")
def nba():
    cluster, conn = load_nba()
    yield cluster, conn
    conn.close()


def rows(resp):
    return sorted(resp.rows)


# --- GO --------------------------------------------------------------------

def test_go_one_step(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like")
    assert r.columns == ["like._dst"]
    assert rows(r) == [(101,), (102,)]


def test_go_reversely(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like REVERSELY YIELD like._dst AS id")
    assert rows(r) == [(101,), (102,), (106,), (107,), (109,)]


def test_go_bidirect(nba):
    _, conn = nba
    r = conn.must("GO FROM 102 OVER like BIDIRECT YIELD like._dst AS id")
    # out: 100; in: 100, 101
    assert sorted(r.rows) == [(100,), (100,), (101,)]


def test_go_two_steps(nba):
    _, conn = nba
    r = conn.must("GO 2 STEPS FROM 100 OVER like YIELD DISTINCT like._dst")
    # step1: 101,102 ; step2 from them: 100,102 / 100
    assert rows(r) == [(100,), (102,)]


def test_go_yield_props_and_where(nba):
    _, conn = nba
    r = conn.must('GO FROM 100 OVER like WHERE like.likeness > 92 '
                  'YIELD like._dst AS id, like.likeness AS w, $^.player.name AS me')
    assert r.columns == ["id", "w", "me"]
    assert rows(r) == [(101, 95.0, "Tim Duncan")]


def test_go_dst_props(nba):
    _, conn = nba
    r = conn.must('GO FROM 100 OVER serve YIELD $$.team.name AS team')
    assert rows(r) == [("Spurs",)]


def test_go_where_dst_prop_not_pushable(nba):
    _, conn = nba
    r = conn.must('GO FROM 100 OVER like WHERE $$.player.age > 33 '
                  'YIELD like._dst AS id, $$.player.age AS age')
    assert rows(r) == [(101, 36)]


def test_go_over_star(nba):
    _, conn = nba
    r = conn.must("GO FROM 101 OVER * YIELD _dst AS d")
    # like: 100, 102 ; serve: 204
    assert rows(r) == [(100,), (102,), (204,)]


def test_go_pipe(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id | "
                  "GO FROM $-.id OVER serve YIELD $$.team.name AS team")
    assert rows(r) == [("Spurs",), ("Spurs",), ("Trail Blazers",)]


def test_go_pipe_input_prop(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w | "
                  "GO FROM $-.id OVER like YIELD $-.w AS base, like.likeness AS w2")
    # from 101 (base 95): ->100 (95), ->102 (91); from 102 (base 90): ->100 (75)
    assert rows(r) == [(90.0, 75.0), (95.0, 91.0), (95.0, 95.0)]


def test_go_variable(nba):
    _, conn = nba
    r = conn.must("$a = GO FROM 100 OVER like YIELD like._dst AS id; "
                  "GO FROM $a.id OVER serve YIELD $$.team.name AS t")
    assert rows(r) == [("Spurs",), ("Spurs",), ("Trail Blazers",)]


def test_go_empty_frontier(nba):
    _, conn = nba
    r = conn.must("GO FROM 121 OVER like")  # Useless has no edges
    assert r.rows == []


def test_go_uuid_from(nba):
    _, conn = nba
    conn.must('INSERT VERTEX player(name, age) VALUES uuid("Special"):("Special", 1)')
    conn.must('INSERT EDGE like(likeness) VALUES uuid("Special") -> 100:(99.0)')
    r = conn.must('GO FROM uuid("Special") OVER like')
    assert rows(r) == [(100,)]


# --- result shaping --------------------------------------------------------

def test_order_by_and_limit(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w "
                  "| ORDER BY $-.w DESC | LIMIT 1")
    assert r.rows == [(101, 95.0)]
    r = conn.must("GO FROM 100 OVER like REVERSELY YIELD like._dst AS id "
                  "| ORDER BY $-.id | LIMIT 1, 2")
    assert r.rows == [(102,), (106,)]


def test_group_by(nba):
    _, conn = nba
    r = conn.must(
        "GO FROM 204 OVER serve REVERSELY YIELD serve.start_year AS y, like._dst AS d"
    ) if False else None
    r = conn.must(
        "GO FROM 100, 101 OVER serve YIELD $$.team.name AS team, serve.start_year AS y "
        "| GROUP BY $-.team YIELD $-.team AS team, COUNT(*) AS n, MIN($-.y) AS first")
    assert rows(r) == [("Spurs", 2, 1997)]


def test_set_ops(nba):
    _, conn = nba
    # bare UNION implies DISTINCT (reference parser.yy:1110-1121)
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id UNION "
                  "GO FROM 101 OVER like YIELD like._dst AS id")
    assert rows(r) == [(100,), (101,), (102,)]
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id UNION ALL "
                  "GO FROM 101 OVER like YIELD like._dst AS id")
    assert rows(r) == [(100,), (101,), (102,), (102,)]
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id INTERSECT "
                  "GO FROM 101 OVER like YIELD like._dst AS id")
    assert rows(r) == [(102,)]
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id MINUS "
                  "GO FROM 101 OVER like YIELD like._dst AS id")
    assert rows(r) == [(101,)]


def test_yield_constant_and_where(nba):
    _, conn = nba
    r = conn.must("YIELD 1 + 2 AS x, \"hello\" AS s")
    assert r.rows == [(3, "hello")]
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w "
                  "| YIELD $-.id AS id WHERE $-.w > 92")
    assert rows(r) == [(101,)]


# --- FETCH -----------------------------------------------------------------

def test_fetch_vertices(nba):
    _, conn = nba
    r = conn.must("FETCH PROP ON player 100, 101")
    assert r.columns == ["VertexID", "player.name", "player.age"]
    assert rows(r) == [(100, "Tim Duncan", 42), (101, "Tony Parker", 36)]
    r = conn.must("FETCH PROP ON player 100 YIELD player.name AS name")
    assert r.rows == [(100, "Tim Duncan")]


def test_fetch_edges(nba):
    _, conn = nba
    r = conn.must("FETCH PROP ON like 100->101")
    assert r.columns == ["like._src", "like._dst", "like._rank", "like.likeness"]
    assert r.rows == [(100, 101, 0, 95.0)]


def test_fetch_from_pipe(nba):
    _, conn = nba
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS id "
                  "| FETCH PROP ON player $-.id YIELD player.name AS name")
    assert rows(r) == [(101, "Tony Parker"), (102, "LaMarcus Aldridge")]


# --- FIND PATH -------------------------------------------------------------

def test_shortest_path_direct(nba):
    _, conn = nba
    r = conn.must("FIND SHORTEST PATH FROM 100 TO 102 OVER like UPTO 4 STEPS")
    assert r.columns == ["_path_"]
    assert r.rows == [("100<like,0>102",)]


def test_shortest_path_two_hops(nba):
    _, conn = nba
    r = conn.must("FIND SHORTEST PATH FROM 103 TO 106 OVER like UPTO 5 STEPS")
    assert r.rows == [("103<like,0>104<like,0>105<like,0>106",)]


def test_shortest_path_none(nba):
    _, conn = nba
    r = conn.must("FIND SHORTEST PATH FROM 100 TO 121 OVER like UPTO 3 STEPS")
    assert r.rows == []


def test_all_paths(nba):
    _, conn = nba
    r = conn.must("FIND ALL PATH FROM 100 TO 102 OVER like UPTO 2 STEPS")
    assert sorted(r.rows) == [("100<like,0>101<like,0>102",),
                              ("100<like,0>102",)]


# --- mutations through nGQL ------------------------------------------------

def test_update_and_upsert(nba):
    _, conn = nba
    conn.must('INSERT VERTEX player(name, age) VALUES 300:("Up Datable", 20)')
    r = conn.must("UPDATE VERTEX 300 SET age = age + 1 WHEN age == 20 YIELD age")
    assert r.rows == [(21,)]
    resp = conn.execute("UPDATE VERTEX 300 SET age = 99 WHEN age == 20")
    assert resp.code == ErrorCode.E_FILTER_OUT
    r = conn.must("UPSERT VERTEX 301 SET age = 5 YIELD age")
    assert r.rows == [(5,)]


def test_update_edge_ngql(nba):
    _, conn = nba
    conn.must('INSERT EDGE like(likeness) VALUES 300 -> 100:(10.0)')
    conn.must("UPDATE EDGE 300 -> 100 OF like SET likeness = 20.0")
    r = conn.must("FETCH PROP ON like 300->100 YIELD like.likeness AS w")
    assert r.rows == [(20.0,)]


def test_delete_vertex_ngql(nba):
    _, conn = nba
    conn.must('INSERT VERTEX player(name, age) VALUES 400:("Doomed", 1)')
    conn.must('INSERT EDGE like(likeness) VALUES 400 -> 100:(50.0), 100 -> 400:(50.0)')
    conn.must("DELETE VERTEX 400")
    r = conn.must("FETCH PROP ON player 400")
    assert r.rows == []
    r = conn.must("GO FROM 100 OVER like")
    assert (400,) not in r.rows


# --- errors ----------------------------------------------------------------

def test_errors(nba):
    _, conn = nba
    resp = conn.execute("GO FROM 100 OVER nonexistent")
    assert resp.code == ErrorCode.E_EDGE_NOT_FOUND
    resp = conn.execute("THIS IS NOT NGQL")
    assert resp.code == ErrorCode.E_SYNTAX_ERROR
    resp = conn.execute("FETCH PROP ON nop 1")
    assert resp.code == ErrorCode.E_TAG_NOT_FOUND


def test_use_required(nba):
    cluster, _ = nba
    c2 = cluster.connect()
    resp = c2.execute("GO FROM 100 OVER like")
    assert resp.code == ErrorCode.E_EXECUTION_ERROR
    assert "USE" in resp.error_msg
    c2.close()


def test_go_upto_accumulates_steps(nba):
    _, conn = nba
    # 103 -> 104 -> 105: UPTO 2 returns both 1-step and 2-step neighbors
    r = conn.must("GO UPTO 2 STEPS FROM 103 OVER like YIELD like._dst AS id")
    assert rows(r) == [(104,), (105,)]
    r = conn.must("GO 2 STEPS FROM 103 OVER like YIELD like._dst AS id")
    assert rows(r) == [(105,)]


def test_group_by_output_alias_reference_parity(nba):
    """GROUP BY may name one of the yield's OWN output aliases (ref
    GroupByLimitTest.cpp:308-318: GROUP BY teamName, start_year with
    teamName defined by the yield); unknown bare names stay errors."""
    _, conn = nba
    r = conn.must(
        "GO FROM 100, 101, 102 OVER serve "
        "YIELD $$.team.name AS name, serve.start_year AS start "
        "| GROUP BY teamName YIELD $-.name AS teamName, "
        "MAX($-.start) AS mx, COUNT(*) AS n")
    rows = sorted(r.rows)
    assert ("Spurs", 2015, 3) in rows and len(rows) == 2
    r2 = conn.execute("GO FROM 100 OVER serve YIELD serve._dst AS d "
                      "| GROUP BY nope YIELD COUNT(*)")
    assert not r2.ok()


def test_fetch_edges_input_refs_reference_parity(nba):
    """FETCH PROP ON <edge> $-.src->$-.dst and $var.src->$var.dst (ref
    FetchEdgesTest.cpp input-ref forms)."""
    _, conn = nba
    r = conn.must("GO FROM 100 OVER serve YIELD serve._src AS src, "
                  "serve._dst AS dst | FETCH PROP ON serve "
                  "$-.src->$-.dst YIELD serve.start_year")
    assert [row[-1] for row in r.rows] == [1997]
    r = conn.must("$a = GO FROM 100, 101 OVER serve YIELD serve._src "
                  "AS src, serve._dst AS dst; FETCH PROP ON serve "
                  "$a.src->$a.dst YIELD serve.start_year")
    assert sorted(row[-1] for row in r.rows) == [1997, 1999]


def test_yield_star_and_var_rows_reference_parity(nba):
    """YIELD $var.* / $-.* expand to every column of the referenced
    table, and a standalone YIELD over one $var iterates the var's
    ROWS (ref YieldTest yieldVar: one output row per var row)."""
    _, conn = nba
    conn.must("INSERT EDGE serve(start_year, end_year) "
              "VALUES 100 -> 201:(2016, 2018)")
    try:
        pre = ("$var = GO FROM 100 OVER serve YIELD "
               "$^.player.name AS name, serve.start_year AS start, "
               "$$.team.name AS team; ")
        r = conn.must(pre + "YIELD $var.*")
        assert sorted(r.rows) == [("Tim Duncan", 1997, "Spurs"),
                                  ("Tim Duncan", 2016, "Nuggets")]
        assert r.columns == ["name", "start", "team"]
        r = conn.must(pre + "YIELD $var.team WHERE $var.start > 2000")
        assert r.rows == [("Nuggets",)]
        r = conn.must(pre + "YIELD AVG($var.start) AS a, COUNT(*) AS n")
        assert r.rows == [((1997 + 2016) / 2, 2)]
        r = conn.must("GO FROM 100 OVER like YIELD like._dst AS d, "
                      "like.likeness AS w | YIELD $-.*")
        assert r.columns == ["d", "w"] and len(r.rows) == 2
    finally:
        conn.must("DELETE EDGE serve 100 -> 201")
