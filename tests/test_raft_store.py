"""Raft-replicated storage tests — the reference's ThreeCopiesTest
(ref kvstore/test/NebulaStoreTest.cpp) and the leader-redirecting
StorageClient path (ref storage/test/StorageClientTest.cpp)."""
import time

import pytest

from nebula_tpu.codec import Schema, SchemaField, PropType, RowReader
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.kvstore.raft_store import ReplicatedStores
from nebula_tpu.meta.schema_manager import AdHocSchemaManager
from nebula_tpu.storage import (NewEdge, NewVertex, StorageClient,
                                StorageService)

FAST = dict(heartbeat_interval=0.06, election_timeout=0.2, rpc_timeout=0.5)


@pytest.fixture
def stores3(tmp_path):
    rs = ReplicatedStores(3, str(tmp_path), **FAST)
    yield rs
    rs.stop()


def test_three_copies_replicate_writes(stores3):
    stores3.add_part(1, 1)
    leader_addr = stores3.leader_of(1, 1)
    leader_store = stores3.stores[leader_addr]

    st = leader_store.async_multi_put(1, 1, [(b"\x01k1", b"v1"),
                                             (b"\x01k2", b"v2")])
    assert st.ok(), st
    # every replica's engine converges on the same data
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        vals = [stores3.stores[a].space_engine(1).get(b"\x01k1")
                for a in stores3.addrs]
        if all(v == b"v1" for v in vals):
            break
        time.sleep(0.02)
    for a in stores3.addrs:
        eng = stores3.stores[a].space_engine(1)
        assert eng.get(b"\x01k1") == b"v1"
        assert eng.get(b"\x01k2") == b"v2"


def test_follower_write_rejected_with_leader_hint(stores3):
    stores3.add_part(1, 1)
    # the follower learns who leads from the first heartbeat AFTER the
    # election — under load its hint can briefly lag (or leadership can
    # move between observation and write), so settle within a bound
    deadline = time.monotonic() + 5
    while True:
        leader_addr = stores3.leader_of(1, 1)
        follower = next(a for a in stores3.addrs if a != leader_addr)
        st = stores3.stores[follower].async_multi_put(
            1, 1, [(b"\x01x", b"y")])
        settled = (st.code == ErrorCode.E_LEADER_CHANGED
                   and st.msg == leader_addr)
        if settled or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert st.code == ErrorCode.E_LEADER_CHANGED
    assert st.msg == leader_addr


def test_follower_read_rejected(stores3):
    stores3.add_part(1, 1)
    leader_addr = stores3.leader_of(1, 1)
    follower = next(a for a in stores3.addrs if a != leader_addr)
    r = stores3.stores[follower].get(1, 1, b"\x01k")
    assert r.status.code == ErrorCode.E_LEADER_CHANGED


def test_atomic_op_through_raft(stores3):
    stores3.add_part(1, 1)
    leader_addr = stores3.leader_of(1, 1)
    store = stores3.stores[leader_addr]
    from nebula_tpu.kvstore import log_encoder as le

    def cas():
        # read-modify-write at the serialization point
        cur = store.space_engine(1).get(b"\x01counter")
        n = int(cur or b"0") + 1
        return le.encode_single(le.OP_PUT, b"\x01counter", str(n).encode())

    for _ in range(5):
        assert store.async_atomic_op(1, 1, cas).ok()
    assert store.space_engine(1).get(b"\x01counter") == b"5"


def _setup_cluster_services(rs, parts=4):
    """StorageService per replica + a client routing by leader cache."""
    sm = AdHocSchemaManager()
    sm.set_num_parts(1, parts)
    sm.add_tag(1, 10, "person",
               Schema([SchemaField("name", PropType.STRING),
                       SchemaField("age", PropType.INT)]))
    sm.add_edge(1, 20, "knows", Schema([SchemaField("w", PropType.INT)]))
    for p in range(1, parts + 1):
        rs.add_part(1, p)
    for p in range(1, parts + 1):
        rs.leader_of(1, p)   # waitUntilLeaderElected
    services = {a: StorageService(rs.stores[a], sm) for a in rs.addrs}
    client = StorageClient(
        sm, hosts=services,
        part_to_host=lambda s, p: rs.addrs[(p - 1) % len(rs.addrs)])
    return sm, services, client


def test_storage_client_redirects_to_leaders(stores3):
    """The client's initial part→host guesses are mostly wrong; redirect
    retries with leader-cache updates must still land every write."""
    sm, services, client = _setup_cluster_services(stores3)
    from nebula_tpu.codec import RowWriter

    vids = list(range(1, 21))
    schema = sm.tag_schema(1, 10).value()
    nvs = [NewVertex(vid, [(10, RowWriter(schema).set("name", f"p{vid}")
                            .set("age", 20 + vid).encode())])
           for vid in vids]
    resp = client.add_vertices(1, nvs)
    assert all(r.code == ErrorCode.SUCCEEDED for r in resp.results.values()), \
        resp.results
    edges = [NewEdge(v, 20, 0, v % 20 + 1,
                     RowWriter(sm.edge_schema(1, 20).value()).set("w", v).encode())
             for v in vids]
    resp = client.add_edges(1, edges)
    assert all(r.code == ErrorCode.SUCCEEDED for r in resp.results.values())

    # reads fan out to leaders and gather every neighbor
    bound = client.get_neighbors(1, vids, [20])
    assert all(r.code == ErrorCode.SUCCEEDED for r in bound.results.values())
    got = {(vd.vid, e.dst) for vd in bound.vertices for e in vd.edges}
    assert got == {(v, v % 20 + 1) for v in vids}


def test_storage_survives_leader_failover(stores3):
    sm, services, client = _setup_cluster_services(stores3, parts=2)
    from nebula_tpu.codec import RowWriter
    schema = sm.tag_schema(1, 10).value()

    def put(vid):
        row = RowWriter(schema).set("name", f"p{vid}").set("age", vid).encode()
        return client.add_vertices(1, [NewVertex(vid, [(10, row)])])

    assert all(r.code == ErrorCode.SUCCEEDED
               for r in put(1).results.values())

    # kill the leader of part 1 (isolate its raft traffic)
    victim = stores3.leader_of(1, 1)
    stores3.net.isolate(victim)
    # a new leader emerges; retries route around the dead host
    deadline = time.monotonic() + 5
    ok = False
    while time.monotonic() < deadline:
        r = put(100)   # vid 100 -> part (100 % 2) + 1 = 1
        if all(x.code == ErrorCode.SUCCEEDED for x in r.results.values()):
            ok = True
            break
        time.sleep(0.1)
    assert ok, "write did not succeed after failover"
