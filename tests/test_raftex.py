"""Raft consensus tests — the reference's raftex test matrix (ref
kvstore/raftex/test/: LeaderElectionTest, LogAppendTest, LogCASTest,
LeaderTransferTest, MemberChangeTest, LearnerTest, SnapshotTest)."""
import time

import pytest

from nebula_tpu.kvstore.raftex import RaftCode, Role
from raft_fixture import RaftCluster


@pytest.fixture
def cluster3(tmp_path):
    c = RaftCluster(3, tmp_path)
    yield c
    c.stop()


# ---------------------------------------------------------------- election

def test_single_replica_becomes_leader(tmp_path):
    c = RaftCluster(1, tmp_path)
    try:
        leader = c.wait_leader()
        assert leader.is_leader()
    finally:
        c.stop()


def test_three_copies_elect_one_leader(cluster3):
    leader = cluster3.wait_leader()
    # followers agree on who the leader is
    time.sleep(0.3)
    for addr, part in cluster3.parts.items():
        assert part.leader() == leader.addr, part.status()


def test_reelection_after_leader_isolated(cluster3):
    leader = cluster3.wait_leader()
    old = leader.addr
    cluster3.isolate(old)
    others = [a for a in cluster3.voting if a != old]
    new_leader = cluster3.wait_leader(among=others)
    assert new_leader.addr != old
    # healed old leader rejoins as follower
    cluster3.heal(old)
    time.sleep(0.5)
    assert not cluster3.parts[old].is_leader()
    assert cluster3.parts[old].leader() == new_leader.addr


def test_no_quorum_no_leader(tmp_path):
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        # isolate two of three: nobody can win an election
        for a in c.voting:
            if a != leader.addr:
                c.isolate(a)
        time.sleep(1.0)
        # old leader may still think it leads (no lease), but the two
        # isolated nodes must not elect anything among themselves
        isolated = [a for a in c.voting if a != leader.addr]
        for a in isolated:
            assert not c.parts[a].is_leader()
    finally:
        c.stop()


# ---------------------------------------------------------------- append

def test_append_replicates_to_all(cluster3):
    leader = cluster3.wait_leader()
    for i in range(10):
        fut = leader.append_async(b"entry-%d" % i)
        assert fut.result(timeout=3) is RaftCode.SUCCEEDED
    cluster3.wait_commit(10)
    datas = [tuple(cluster3.shards[a].data()) for a in cluster3.voting]
    assert datas[0] == datas[1] == datas[2]
    assert datas[0] == tuple(b"entry-%d" % i for i in range(10))


def test_append_on_follower_rejected(cluster3):
    leader = cluster3.wait_leader()
    follower = next(p for a, p in cluster3.parts.items()
                    if a != leader.addr)
    assert follower.append_async(b"nope").result(timeout=2) is \
        RaftCode.E_NOT_A_LEADER


def test_concurrent_appends_coalesce(cluster3):
    leader = cluster3.wait_leader()
    futs = [leader.append_async(b"c%03d" % i) for i in range(100)]
    for f in futs:
        assert f.result(timeout=5) is RaftCode.SUCCEEDED
    cluster3.wait_commit(100)
    # commit order matches append order on every replica
    for a in cluster3.voting:
        assert cluster3.shards[a].data() == [b"c%03d" % i for i in range(100)]


def test_injected_follower_wal_append_failure_keeps_quorum(cluster3):
    """Satellite (ISSUE 8): the durability path has fault-injection
    coverage — an injected `wal.append` failure on ONE follower (the
    full-disk failure shape: Wal.append returns False, the follower
    answers E_WAL_FAIL) must neither break quorum commit (leader +
    surviving follower = 2/3) nor wedge the part: the failed follower
    catches up on the next replication round once the fault clears."""
    from nebula_tpu.common.faults import faults
    leader = cluster3.wait_leader()
    assert leader.append_async(b"pre").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)
    try:
        # after=1 skips the leader's own local append; n=1 fails
        # exactly one of the two follower replication appends
        faults.set_plan("wal.append:after=1,n=1")
        fut = leader.append_async(b"quorum-entry")
        assert fut.result(timeout=5) is RaftCode.SUCCEEDED
        fired = faults.counts().get("wal.append", 0)
    finally:
        faults.reset()
    assert fired == 1, "the injected follower append never fired"
    # no wedge: ALL replicas converge (the failed follower's match_id
    # stayed behind, so the replicator re-shipped the entry)
    cluster3.wait_commit(2)
    datas = [tuple(cluster3.shards[a].data()) for a in cluster3.voting]
    assert datas[0] == datas[1] == datas[2] == (b"pre", b"quorum-entry")
    # and the part still serves: a follow-up append commits everywhere
    assert leader.append_async(b"post").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(3)


def test_wal_sync_every_append_flag_consumed_at_bind(tmp_path):
    """Satellite (ISSUE 8): the `wal_sync_every_append` storaged gflag
    (REBOOT, read at part bind) reaches the Wal constructor —
    docs/manual/12-replication.md durability caveats."""
    from nebula_tpu.common.flags import storage_flags
    assert storage_flags.get("wal_sync_every_append") is False
    storage_flags.set("wal_sync_every_append", True)
    try:
        c = RaftCluster(1, tmp_path)
        try:
            assert all(p.wal.sync_every_append
                       for p in c.parts.values())
        finally:
            c.stop()
    finally:
        storage_flags.set("wal_sync_every_append", False)
    c2 = RaftCluster(1, tmp_path / "off")
    try:
        assert not any(p.wal.sync_every_append
                       for p in c2.parts.values())
    finally:
        c2.stop()


def test_append_survives_leader_change(cluster3):
    leader = cluster3.wait_leader()
    for i in range(5):
        leader.append_async(b"pre-%d" % i).result(timeout=3)
    cluster3.wait_commit(5)
    cluster3.isolate(leader.addr)
    others = [a for a in cluster3.voting if a != leader.addr]
    new_leader = cluster3.wait_leader(among=others)
    for i in range(5):
        assert new_leader.append_async(b"post-%d" % i).result(timeout=3) is \
            RaftCode.SUCCEEDED
    cluster3.wait_commit(10, addrs=others)
    assert cluster3.shards[others[0]].data() == \
        [b"pre-%d" % i for i in range(5)] + [b"post-%d" % i for i in range(5)]
    # healed old leader catches up
    cluster3.heal(leader.addr)
    cluster3.wait_commit(10)


def test_follower_catchup_after_isolation(cluster3):
    leader = cluster3.wait_leader()
    lagging = next(a for a in cluster3.voting if a != leader.addr)
    cluster3.isolate(lagging)
    for i in range(20):
        leader.append_async(b"x%d" % i).result(timeout=3)
    up = [a for a in cluster3.voting if a != lagging]
    cluster3.wait_commit(20, addrs=up)
    cluster3.heal(lagging)
    cluster3.wait_commit(20)   # gap resolution catches the laggard up
    assert cluster3.shards[lagging].data() == [b"x%d" % i for i in range(20)]


# ---------------------------------------------------------------- CAS

def test_atomic_op(cluster3):
    """LogCAS analogue: the closure runs at the serialization point and
    can abort (ref LogCASTest)."""
    leader = cluster3.wait_leader()
    leader.append_async(b"base").result(timeout=3)

    seen = []

    def cas_ok():
        seen.append(1)
        return b"cas-applied"

    def cas_abort():
        return None

    assert leader.atomic_op_async(cas_ok).result(timeout=3) is \
        RaftCode.SUCCEEDED
    assert leader.atomic_op_async(cas_abort).result(timeout=3) is \
        RaftCode.E_BAD_STATE
    cluster3.wait_commit(2)
    for a in cluster3.voting:
        assert cluster3.shards[a].data() == [b"base", b"cas-applied"]


# ---------------------------------------------------------------- transfer

def test_leader_transfer(cluster3):
    leader = cluster3.wait_leader()
    target = next(a for a in cluster3.voting if a != leader.addr)
    leader.transfer_leader_async(target)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if cluster3.parts[target].is_leader():
            break
        time.sleep(0.02)
    assert cluster3.parts[target].is_leader()
    # cluster still works
    new_leader = cluster3.parts[target]
    assert new_leader.append_async(b"after-transfer").result(timeout=3) is \
        RaftCode.SUCCEEDED
    cluster3.wait_commit(1)


# ---------------------------------------------------------------- learner

def test_learner_replicates_but_does_not_vote(tmp_path):
    c = RaftCluster(3, tmp_path, learners=1)
    try:
        learner_addr = c.addrs[3]
        leader = c.wait_leader()
        leader.add_learner_async(learner_addr).result(timeout=3)
        for i in range(5):
            leader.append_async(b"L%d" % i).result(timeout=3)
        c.wait_commit(5, addrs=[learner_addr])
        assert c.shards[learner_addr].data() == [b"L%d" % i for i in range(5)]
        assert c.parts[learner_addr].role is Role.LEARNER
        assert not c.parts[learner_addr].is_leader()
    finally:
        c.stop()


# ---------------------------------------------------------------- membership

def test_member_change_add_peer(tmp_path):
    c = RaftCluster(3, tmp_path, learners=1)
    try:
        new_addr = c.addrs[3]
        leader = c.wait_leader()
        leader.add_learner_async(new_addr).result(timeout=3)
        for i in range(5):
            leader.append_async(b"m%d" % i).result(timeout=3)
        c.wait_commit(5, addrs=[new_addr])
        # promote: learner becomes a voting member
        leader.add_peer_async(new_addr).result(timeout=3)
        time.sleep(0.3)
        assert new_addr in leader.peers
        assert c.parts[new_addr].role is Role.FOLLOWER
        assert leader.append_async(b"post-add").result(timeout=3) is \
            RaftCode.SUCCEEDED
        c.wait_commit(6, addrs=[new_addr])
    finally:
        c.stop()


def test_member_change_remove_peer(cluster3):
    leader = cluster3.wait_leader()
    victim = next(a for a in cluster3.voting if a != leader.addr)
    leader.remove_peer_async(victim).result(timeout=3)
    deadline = time.time() + 5   # fixed sleeps flake on a loaded box
    while victim in leader.peers and time.time() < deadline:
        time.sleep(0.05)
    assert victim not in leader.peers
    # two-member cluster still commits
    assert leader.append_async(b"post-remove").result(timeout=3) is \
        RaftCode.SUCCEEDED


# ---------------------------------------------------------------- snapshot

def test_snapshot_catchup_when_wal_evicted(tmp_path):
    """A rejoining follower whose needed logs were TTL-evicted from the
    leader's WAL receives a full snapshot instead (ref SnapshotTest)."""
    c = RaftCluster(3, tmp_path, wal_ttl_secs=0, wal_file_size=512)
    try:
        leader = c.wait_leader()
        lagging = next(a for a in c.voting if a != leader.addr)
        c.isolate(lagging)
        for i in range(30):
            leader.append_async(b"s%02d" % i).result(timeout=3)
        up = [a for a in c.voting if a != lagging]
        c.wait_commit(30, addrs=up)
        # evict the leader's sealed WAL segments
        leader.wal._lib  # ensure loaded
        # force multi-segment by rolling: append enough, then clean
        removed = leader.wal.clean_ttl()
        if removed == 0:
            pytest.skip("wal stayed single-segment; nothing evicted")
        c.heal(lagging)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(c.shards[lagging].snapshot_rows) > 0 or \
                    len(c.shards[lagging].data()) >= 30:
                break
            time.sleep(0.05)
        assert c.shards[lagging].snapshot_rows or \
            len(c.shards[lagging].data()) >= 30
    finally:
        c.stop()


# ---------------------------------------------------------------- restart

def test_restart_recovers_from_wal(tmp_path):
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        for i in range(8):
            leader.append_async(b"r%d" % i).result(timeout=3)
        c.wait_commit(8)
        victim = next(a for a in c.voting if a != leader.addr)
        c.kill(victim)
        for i in range(8, 12):
            leader.append_async(b"r%d" % i).result(timeout=3)
        c.restart(victim)
        c.wait_commit(12)
        assert c.shards[victim].data() == [b"r%d" % i for i in range(12)]
    finally:
        c.stop()


# ------------------------------------------------- raftex over real TCP

def test_rpc_transport_election_and_replication(tmp_path):
    """The production transport shape: raft groups over framed-TCP
    rpc/ servers (RaftexService registered as "raftex"), electing and
    replicating across real sockets."""
    from raft_fixture import RpcRaftCluster

    c = RpcRaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader(timeout=8.0)
        for i in range(6):
            assert leader.append_async(b"t%d" % i).result(timeout=5) \
                is RaftCode.SUCCEEDED
        c.wait_commit(6, timeout=8.0)
        for addr in c.addrs:
            assert c.shards[addr].data() == [b"t%d" % i
                                             for i in range(6)]
    finally:
        c.stop()


def test_rpc_transport_partitioned_leader_reelection(tmp_path):
    """Failover regression (satellite): a PARTITIONED leader over the
    TCP transport — the survivors elect a replacement, keep committing,
    and the deposed leader steps down (check-quorum) and catches up on
    heal instead of serving a divergent history."""
    from raft_fixture import RpcRaftCluster

    c = RpcRaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader(timeout=8.0)
        old = leader.addr
        for i in range(4):
            assert leader.append_async(b"a%d" % i).result(timeout=5) \
                is RaftCode.SUCCEEDED
        c.wait_commit(4, timeout=8.0)

        c.isolate(old)
        others = [a for a in c.addrs if a != old]
        new_leader = c.wait_leader(timeout=8.0, among=others)
        assert new_leader.addr != old
        # the survivors commit through the NEW leader while the old one
        # is cut off
        for i in range(4, 8):
            assert new_leader.append_async(b"a%d" % i).result(timeout=5) \
                is RaftCode.SUCCEEDED
        c.wait_commit(8, timeout=8.0, addrs=others)
        # check-quorum: the isolated leader must step down rather than
        # keep acknowledging reads as a zombie leader
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and c.parts[old].is_leader():
            time.sleep(0.05)
        assert not c.parts[old].is_leader()
        # appends on the deposed leader fail fast with a redirect code
        code = c.parts[old].append_async(b"zombie").result(timeout=5)
        assert code is RaftCode.E_NOT_A_LEADER

        c.heal(old)
        c.wait_commit(8, timeout=8.0)
        assert c.shards[old].data() == [b"a%d" % i for i in range(8)]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                c.parts[old].leader() != new_leader.addr:
            time.sleep(0.05)
        assert c.parts[old].leader() == new_leader.addr
    finally:
        c.stop()
