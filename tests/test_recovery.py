"""Crash-recovery tests (ISSUE 11 tier-1): same-dir restart replay,
mid-snapshot-install crash convergence, corrupted raft_state fallback,
and the compaction safety invariant — the in-process twins of what
`bench.py --crash` proves with real SIGKILLed subprocesses
(docs/manual/12-replication.md, "Crash recovery & compaction")."""
import os
import time

import pytest

from nebula_tpu.common import keys as keyutils
from nebula_tpu.common.flight import recorder as flight
from nebula_tpu.common.stats import stats
from nebula_tpu.kvstore.raft_store import StorageNode
from nebula_tpu.kvstore.raftex import InProcNetwork, RaftCode, Role
from nebula_tpu.kvstore.raftex.types import SendSnapshotRequest
from raft_fixture import FAST, RaftCluster

ADDRS = ["n0", "n1", "n2"]


def _mk_nodes(tmp_path, net, **raft_kw):
    kw = {**FAST, **raft_kw}
    return {a: StorageNode(a, str(tmp_path), net, **kw) for a in ADDRS}


def _wait_leader(nodes, sid=1, pid=1, timeout=6.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [a for a, n in nodes.items()
                   if n.raft(sid, pid) is not None
                   and n.raft(sid, pid).is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader")


def _part_rows(node, sid=1, pid=1):
    eng = node.store.space_engine(sid)
    return sorted((k, v) for k, v in
                  eng.prefix(keyutils.part_data_prefix(pid, 0x01)))


def _wait_rows_equal(a, b, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ra, rb = _part_rows(a), _part_rows(b)
        if ra == rb and ra:
            return ra
        time.sleep(0.05)
    raise AssertionError(f"engines never converged:\n{_part_rows(a)}\n"
                         f"vs\n{_part_rows(b)}")


def _kv(i: int):
    return (keyutils.part_data_prefix(1, 0x01) + b"k%04d" % i,
            b"v%04d" % i)


# ---------------------------------------------------------------- restart

def test_same_dir_restart_replays_tail_and_serves_identical_bytes(tmp_path):
    """A storage node killed and re-bound on its OWN data dir replays
    the WAL tail through the normal commit_logs path and converges to
    byte-identical part contents — including writes that landed while
    it was down. The replay is visible: wal_replayed > 0 on the
    restarted part and a `wal_replay` flight event in the ring."""
    net = InProcNetwork()
    nodes = _mk_nodes(tmp_path, net)
    try:
        for a in ADDRS:
            nodes[a].add_part(1, 1, ADDRS)
        leader = _wait_leader(nodes)
        store = nodes[leader].store
        assert store.async_multi_put(
            1, 1, [_kv(i) for i in range(20)]).ok()
        victim = next(a for a in ADDRS if a != leader)
        _wait_rows_equal(nodes[leader], nodes[victim])

        nodes[victim].stop()            # "kill": raft + service down
        assert store.async_multi_put(
            1, 1, [_kv(i) for i in range(20, 35)]).ok()

        # restart on the SAME data dir: fresh engines (marker 0 for
        # the in-memory engine — the worst case), full WAL replay
        nodes[victim] = StorageNode(victim, str(tmp_path), net, **FAST)
        nodes[victim].add_part(1, 1, ADDRS)
        rows = _wait_rows_equal(nodes[leader], nodes[victim])
        assert len(rows) == 35          # identical bytes incl. the gap
        st = nodes[victim].raft(1, 1).status()
        assert st["wal_replayed"] > 0
        assert st["wal_replay_done"] is True
        evs = [e for e in flight.describe(limit=400)["events"]
               if e["kind"] == "wal_replay" and e.get("addr") == victim]
        assert evs, "no wal_replay flight event for the restart"
        assert evs[0]["n"] == st["wal_replayed"]
    finally:
        for n in nodes.values():
            n.stop()
        net.shutdown()


def test_restarted_member_with_history_is_not_a_learner(tmp_path):
    """The topology-join heuristic flags restarted parts as learners
    (group already formed elsewhere) — but a replica with durable WAL/
    term history is a returning MEMBER; staying a learner would
    silently shrink the voting set (RaftPart same-dir restart
    fencing)."""
    c = RaftCluster(3, tmp_path)
    try:
        leader = c.wait_leader()
        for i in range(5):
            leader.append_async(b"m%d" % i).result(timeout=3)
        c.wait_commit(5)
        victim = next(a for a in c.voting if a != leader.addr)
        c.kill(victim)
        part = c.restart(victim, is_learner=True)   # heuristic verdict
        assert part.role is not Role.LEARNER        # history overrides
        c.wait_commit(5)
    finally:
        c.stop()


# ------------------------------------------------------------- snapshot

def test_mid_snapshot_crash_receiver_rerequests_and_converges(tmp_path):
    """A receiver that dies mid-snapshot-install (partial rows applied,
    part prefix cleared, NO commit marker) must be able to re-request
    the snapshot after restart and converge — the in-process twin of
    the `crashpoint.snapshot_recv` cycle in bench --crash."""
    net = InProcNetwork()
    # tiny segments so compaction can actually evict the gap
    nodes = _mk_nodes(tmp_path, net, wal_file_size=512)
    try:
        for a in ADDRS:
            nodes[a].add_part(1, 1, ADDRS)
        leader = _wait_leader(nodes)
        store = nodes[leader].store
        assert store.async_multi_put(
            1, 1, [_kv(i) for i in range(10)]).ok()
        victim = next(a for a in ADDRS if a != leader)
        _wait_rows_equal(nodes[leader], nodes[victim])
        nodes[victim].stop()

        # while the victim is down: enough singles to roll segments,
        # then compact the survivors behind their applied anchor so
        # the victim's gap is truncated -> snapshot is the ONLY way in
        for i in range(10, 60):
            assert store.async_multi_put(1, 1, [_kv(i)]).ok()
        for a in ADDRS:
            if a != victim:
                nodes[a].compact_wals(lag=0)
        lead_raft = nodes[leader].raft(1, 1)
        assert lead_raft.wal.first_log_id > 1, "nothing compacted"

        # restart + simulate the crashpoint: a PARTIAL install (one
        # non-done chunk) lands, then the process dies again
        nodes[victim] = StorageNode(victim, str(tmp_path), net,
                                    **{**FAST,
                                       "wal_file_size": 512})
        nodes[victim].add_part(1, 1, ADDRS)
        vr = nodes[victim].raft(1, 1)
        vr.process_send_snapshot(SendSnapshotRequest(
            space=1, part=1, term=max(vr.term, lead_raft.term),
            leader=leader, committed_log_id=lead_raft.committed_id,
            committed_log_term=lead_raft.wal.last_log_term,
            rows=[_kv(0)], total_size=1, total_count=2, done=False))
        nodes[victim].stop()            # crash between chunks

        # clean restart: marker 0 + truncated gap => the leader must
        # send a FULL snapshot again; the receiver converges
        nodes[victim] = StorageNode(victim, str(tmp_path), net,
                                    **{**FAST,
                                       "wal_file_size": 512})
        nodes[victim].add_part(1, 1, ADDRS)
        rows = _wait_rows_equal(nodes[leader], nodes[victim],
                                timeout=12.0)
        assert len(rows) == 60
        evs = [e for e in flight.describe(limit=400)["events"]
               if e["kind"] == "snapshot_install"
               and e.get("addr") == victim]
        assert evs, "no snapshot_install flight event"
    finally:
        for n in nodes.values():
            try:
                n.stop()
            except Exception:
                pass
        net.shutdown()


# ------------------------------------------------------------ raft_state

def test_corrupted_raft_state_falls_back_without_wedging(tmp_path):
    """A torn/garbage raft_state file is detected by the checksum at
    load, counted + flight-recorded, and the replica falls back to
    defaults instead of silently parsing garbage — and the cluster
    still elects (term catch-up via vote responses)."""
    c = RaftCluster(3, tmp_path)
    state_paths = [p._state_path for p in c.parts.values()]
    try:
        leader = c.wait_leader()
        for i in range(5):
            leader.append_async(b"s%d" % i).result(timeout=3)
        c.wait_commit(5)
        # the hardened writer produced the checksummed 4-line format
        # (term, voted_for, role L|V, crc)
        for sp in state_paths:
            if os.path.exists(sp):
                lines = open(sp).read().splitlines()
                assert len(lines) == 4
                assert lines[2] in ("L", "V")
    finally:
        c.stop()

    # corrupt ONE replica's state file with a torn/garbage write
    with open(state_paths[0], "w") as f:
        f.write("999999\nno-such-candidate\ndeadbeef\n")
    before = stats.lifetime_total("raftex.state_recovered")
    c2 = RaftCluster(3, tmp_path)
    try:
        assert stats.lifetime_total("raftex.state_recovered") > before
        leader = c2.wait_leader(timeout=8.0)   # no wedge
        assert leader.append_async(b"post").result(timeout=5) is \
            RaftCode.SUCCEEDED
    finally:
        c2.stop()


def test_state_file_survives_and_roundtrips(tmp_path):
    """_persist_state -> _load_state round trip across a restart: the
    persisted (term, voted_for) pair comes back verbatim under the
    checksummed format; the legacy 2-line format still parses."""
    c = RaftCluster(1, tmp_path)
    try:
        leader = c.wait_leader()
        term = leader.term
        sp = leader._state_path
        assert term >= 1
    finally:
        c.stop()
    c2 = RaftCluster(1, tmp_path)
    try:
        # the restarted part adopted at least the persisted term
        part = list(c2.parts.values())[0]
        assert part.term >= term
    finally:
        c2.stop()
    # legacy 2-line file (pre-checksum) is accepted, not "recovered"
    with open(sp, "w") as f:
        f.write("7\nsomeone\n")
    before = stats.lifetime_total("raftex.state_recovered")
    c3 = RaftCluster(1, tmp_path)
    try:
        assert stats.lifetime_total("raftex.state_recovered") == before
        assert list(c3.parts.values())[0].term >= 7
    finally:
        c3.stop()


# ------------------------------------------------------------ compaction

def test_boot_tail_membership_commands_reapply_without_crashing(tmp_path):
    """A membership COMMAND left in the boot tail (crash before the
    commit marker covered it) re-applies to the in-memory peer set at
    bind — including REMOVE_PEER, which touches self.hosts and must
    not blow up the constructor."""
    from nebula_tpu.kvstore.raftex import RaftexService
    from nebula_tpu.kvstore.raftex.raft_part import (
        _M_COMMAND, CMD_ADD_LEARNER, CMD_REMOVE_PEER, RaftPart,
        _encode_cmd)
    from nebula_tpu.kvstore.wal import Wal

    wal_dir = str(tmp_path / "boot")
    os.makedirs(wal_dir)
    w = Wal(os.path.join(wal_dir, "wal"))
    w.append(1, 1, 0, b"\x00payload")
    w.append(2, 1, 0, _M_COMMAND + _encode_cmd(CMD_REMOVE_PEER, "nX"))
    w.append(3, 1, 0, _M_COMMAND + _encode_cmd(CMD_ADD_LEARNER, "nL"))
    w.close()
    net = InProcNetwork()
    svc = RaftexService("n0", net)
    part = RaftPart(space_id=1, part_id=1, addr="n0",
                    peers=["n0", "n1", "nX"], wal_dir=wal_dir,
                    service=svc, on_commit=lambda logs: None,
                    applied_id=0, **FAST)
    try:
        assert "nX" not in part.peers       # REMOVE_PEER re-applied
        assert "nL" in part.learners        # ADD_LEARNER re-applied
        assert part.status()["wal_replay_done"] is False
    finally:
        part.stop()
        svc.stop()
        net.shutdown()


def test_compaction_never_truncates_past_unapplied_entries(tmp_path):
    """compact_wal clamps the anchor to committed_id — and bounds the
    TTL sweep by it too (wal_ttl_secs=0 makes every sealed segment
    age-eligible here): entries appended but NOT yet committed (no
    quorum) survive any compaction request, however aggressive the
    caller's anchor/lag and however old the segments."""
    c = RaftCluster(3, tmp_path, wal_file_size=512, wal_ttl_secs=0)
    try:
        leader = c.wait_leader()
        for i in range(60):
            assert leader.append_async(b"c%03d" % i).result(timeout=3) \
                is RaftCode.SUCCEEDED
        c.wait_commit(60)
        committed = leader.committed_id
        # cut the leader off so new appends can NEVER commit
        for a in c.voting:
            if a != leader.addr:
                c.isolate(a)
        futs = [leader.append_async(b"uncommitted-%d" % i)
                for i in range(10)]
        first_unapplied = committed + 1
        tail_last = leader.wal.last_log_id
        assert tail_last >= committed + 10

        # the most aggressive possible request: absurd anchor, lag 0
        out = leader.compact_wal(0, anchor=10 ** 9)
        assert out["anchor"] <= committed
        assert out["removed"] > 0          # sealed prefix did go
        assert leader.wal.first_log_id <= first_unapplied
        got = [e.log_id for e in leader.wal.iterate(first_unapplied,
                                                    tail_last)]
        assert got == list(range(first_unapplied, tail_last + 1)), \
            "an unapplied entry was truncated"
        for a in c.voting:
            c.heal(a)
        for f in futs:
            f.result(timeout=5)
    finally:
        c.stop()


def test_ttl_clean_wired_through_compaction_task_body(tmp_path):
    """Satellite: the orphaned Wal.clean_ttl finally has a caller —
    StorageNode.compact_wals (the storaged background task body) runs
    it per part; `raftex.wal_cleaned` counts the removed segments."""
    net = InProcNetwork()
    nodes = _mk_nodes(tmp_path, net, wal_file_size=512, wal_ttl_secs=0)
    try:
        for a in ADDRS:
            nodes[a].add_part(1, 1, ADDRS)
        leader = _wait_leader(nodes)
        store = nodes[leader].store
        for i in range(60):
            assert store.async_multi_put(1, 1, [_kv(i)]).ok()
        before = stats.lifetime_total("raftex.wal_cleaned")
        # a HUGE lag disables the anchor clean entirely: whatever goes
        # is the TTL sweep's doing (ttl=0 -> every sealed segment)
        out = nodes[leader].compact_wals(lag=10 ** 9)
        assert sum(r["removed"] for r in out.values()) > 0
        assert stats.lifetime_total("raftex.wal_cleaned") > before
        assert nodes[leader].raft(1, 1).wal_cleaned > 0
        # tail intact and the part still serves
        assert store.async_multi_put(1, 1, [_kv(1000)]).ok()
    finally:
        for n in nodes.values():
            n.stop()
        net.shutdown()


def test_evacuation_purges_wal_dir(tmp_path):
    """remove_part deletes the part's WAL + raft_state alongside the
    engine data, so a later re-add of the same part starts clean
    instead of impersonating a same-dir member restart."""
    net = InProcNetwork()
    nodes = _mk_nodes(tmp_path, net)
    try:
        for a in ADDRS:
            nodes[a].add_part(1, 1, ADDRS)
        leader = _wait_leader(nodes)
        assert nodes[leader].store.async_multi_put(
            1, 1, [_kv(0)]).ok()
        victim = next(a for a in ADDRS if a != leader)
        wal_dir = nodes[victim].hooks[(1, 1)].wal_dir
        assert os.path.isdir(wal_dir)
        nodes[victim].remove_part(1, 1)
        assert not os.path.exists(wal_dir)
    finally:
        for n in nodes.values():
            n.stop()
        net.shutdown()
