"""Row codec tests (parity model: dataman/test/RowReaderTest.cpp, RowWriterTest.cpp)."""
import pytest

from nebula_tpu.codec import (PropType, RowReader, RowSetReader, RowSetWriter,
                              RowUpdater, RowWriter, Schema, SchemaField)


def player_schema(version=0):
    return Schema([
        SchemaField("name", PropType.STRING),
        SchemaField("age", PropType.INT),
        SchemaField("score", PropType.DOUBLE),
        SchemaField("active", PropType.BOOL),
    ], version=version)


def test_roundtrip_all_types():
    s = player_schema()
    w = RowWriter(s)
    w.set("name", "Tim Duncan").set("age", 42).set("score", 19.0).set("active", True)
    data = w.encode()
    r = RowReader(s, data)
    assert r.get("name") == "Tim Duncan"
    assert r.get("age") == 42
    assert r.get("score") == 19.0
    assert r.get("active") is True
    assert r.to_dict() == {"name": "Tim Duncan", "age": 42, "score": 19.0, "active": True}


def test_defaults_for_unset_fields():
    s = Schema([
        SchemaField("a", PropType.INT, default=7),
        SchemaField("b", PropType.STRING),
        SchemaField("c", PropType.DOUBLE, nullable=True),
    ])
    data = RowWriter(s).encode()
    r = RowReader(s, data)
    assert r.get("a") == 7        # explicit default
    assert r.get("b") == ""       # type default
    assert r.get("c") is None     # nullable with no default -> null


def test_schema_version_embedded():
    s = player_schema(version=300)
    data = RowWriter(s).set("age", 1).encode()
    assert RowReader.schema_version(data) == 300
    s0 = player_schema(version=0)
    data0 = RowWriter(s0).encode()
    assert RowReader.schema_version(data0) == 0


def test_unicode_and_empty_strings():
    s = Schema([SchemaField("a", PropType.STRING), SchemaField("b", PropType.STRING)])
    data = RowWriter(s).set("a", "héllo 世界").set("b", "").encode()
    r = RowReader(s, data)
    assert r.get("a") == "héllo 世界"
    assert r.get("b") == ""


def test_negative_and_large_ints():
    s = Schema([SchemaField("x", PropType.INT), SchemaField("t", PropType.TIMESTAMP)])
    data = RowWriter(s).set("x", -(1 << 62)).set("t", 1 << 40).encode()
    r = RowReader(s, data)
    assert r.get("x") == -(1 << 62)
    assert r.get("t") == 1 << 40


def test_type_errors():
    s = player_schema()
    w = RowWriter(s)
    with pytest.raises(TypeError):
        w.set("age", "not an int")
    with pytest.raises(KeyError):
        w.set("nope", 1)


def test_updater_overlays_existing_row():
    s = player_schema()
    base = RowWriter(s).set("name", "Tony Parker").set("age", 36).encode()
    u = RowUpdater(s, base)
    u.set("age", 37)
    r = RowReader(s, u.encode())
    assert r.get("name") == "Tony Parker"
    assert r.get("age") == 37


def test_rowset_roundtrip():
    s = player_schema()
    rows = [RowWriter(s).set("name", f"p{i}").set("age", i).encode() for i in range(5)]
    w = RowSetWriter()
    for row in rows:
        w.add_row(row)
    out = list(RowSetReader(w.data()))
    assert out == rows
    ages = [RowReader(s, row).get("age") for row in out]
    assert ages == [0, 1, 2, 3, 4]


def test_schema_evolution():
    s0 = player_schema(version=0)
    s1 = s0.with_added([SchemaField("team", PropType.STRING, default="FA")])
    assert s1.version == 1
    # old rows decodable with old schema resolved by embedded version
    old = RowWriter(s0).set("name", "X").encode()
    assert RowReader.schema_version(old) == 0
    new = RowWriter(s1).set("name", "Y").encode()
    assert RowReader(s1, new).get("team") == "FA"
    s2 = s1.with_dropped(["score"])
    assert not s2.has_field("score")
    assert s2.version == 2


def test_native_encode_rows_identity():
    """ISSUE 1 acceptance: the native batch row-encode
    (nbc_encode_rows), its pure-Python fallback (encode_rows_py) and
    the per-row RowWriter all produce byte-identical blobs, and the
    native decoder round-trips them."""
    import numpy as np
    from nebula_tpu import native

    fields = [SchemaField("a", PropType.INT),
              SchemaField("b", PropType.DOUBLE),
              SchemaField("c", PropType.BOOL),
              SchemaField("d", PropType.STRING),
              SchemaField("e", PropType.INT, nullable=True)]
    schema = Schema(fields=fields, version=9)
    ft = [f.type.value for f in fields]
    rng = np.random.default_rng(5)
    n = 64
    vals_i64 = np.zeros((5, n), np.int64)
    vals_f64 = np.zeros((5, n), np.float64)
    nulls = np.zeros((5, n), bool)
    vals_i64[0] = rng.integers(-2**62, 2**62, n)
    vals_f64[1] = rng.normal(size=n)
    vals_i64[2] = rng.integers(0, 2, n)
    strs = [("val%d" % i) * (i % 5) for i in range(n)]
    blob = b"".join(s.encode("utf-8") for s in strs)
    str_off = np.zeros((5, n), np.int64)
    str_len = np.zeros((5, n), np.uint32)
    pos = 0
    for i, s in enumerate(strs):
        b = s.encode("utf-8")
        str_off[3, i], str_len[3, i] = pos, len(b)
        pos += len(b)
    nulls[4] = rng.integers(0, 2, n).astype(bool)
    vals_i64[4] = rng.integers(0, 1000, n)

    py_blob, py_off, py_len = native.encode_rows_py(
        ft, vals_i64, vals_f64, nulls, blob, str_off, str_len,
        schema_version=9)
    # RowWriter oracle: per-row bytes concatenated
    ref = b""
    for i in range(n):
        w = (RowWriter(schema)
             .set("a", int(vals_i64[0, i]))
             .set("b", float(vals_f64[1, i]))
             .set("c", bool(vals_i64[2, i]))
             .set("d", strs[i])
             .set("e", None if nulls[4, i] else int(vals_i64[4, i])))
        ref += w.encode()
    assert py_blob == ref

    if not native.available():
        pytest.skip("native toolchain unavailable (fallback verified)")
    nat_blob, nat_off, nat_len = native.encode_rows(
        ft, vals_i64, vals_f64, nulls, blob, str_off, str_len,
        schema_version=9)
    assert nat_blob == py_blob
    assert (nat_off == py_off).all() and (nat_len == py_len).all()
    # round-trip through the native batch decoder
    v64, vf, so, sl, nl, _ = native.decode_rows(
        ft, nat_blob, nat_off, nat_len, np.arange(n, dtype=np.int32), n)
    assert (v64[0] == vals_i64[0]).all()
    assert np.allclose(vf[1], vals_f64[1])
    assert (nl[4] == nulls[4]).all()
    got = [nat_blob[so[3, i]:so[3, i] + sl[3, i]].decode("utf-8")
           for i in range(n)]
    assert got == strs
