"""Multi-process-topology cluster over the rpc/ transport.

The reference's key distributed-test idiom: boot REAL servers
in-process on ephemeral localhost ports (ref graph/test/TestEnv.cpp:
29-71, storage/test/StorageClientTest) — here metad + two storaged +
graphd, each behind its own RpcServer socket, exercising the wire
codec, part allocation over heartbeating hosts, the storaged topology
watch, and the network GraphClient end-to-end.
"""
import time

import pytest

from nebula_tpu.client import GraphClient
from nebula_tpu.common.status import ErrorCode, Status, StatusOr
from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
from nebula_tpu.rpc import wire
from nebula_tpu.storage.types import (BoundRequest, BoundResponse, EdgeData,
                                      PartResult, VertexData)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("value", [
    None, True, False, 0, -1, 1 << 40, -(1 << 40), 3.25, "héllo", b"\x00\xff",
    [1, "a", None], (1, (2, 3)), {"k": [1, 2], 5: b"x"},
    ErrorCode.E_LEADER_CHANGED,
    Status.error(ErrorCode.E_NOT_FOUND, "nope"),
    PartResult(ErrorCode.E_LEADER_CHANGED, "h:1"),
    EdgeData(1, -2, 0, 9, {"w": 1.5}),
])
def test_wire_roundtrip(value):
    assert wire.decode(wire.encode(value)) == value


def test_wire_statusor_roundtrip():
    r = wire.decode(wire.encode(StatusOr.of([1, 2])))
    assert r.ok() and r.value() == [1, 2]
    e = wire.decode(wire.encode(StatusOr.err(ErrorCode.E_EXISTED, "x")))
    assert not e.ok() and e.status.code == ErrorCode.E_EXISTED


def test_wire_nested_response():
    resp = BoundResponse(results={1: PartResult()},
                         vertices=[VertexData(7, {1: {"name": "x"}},
                                              [EdgeData(7, 1, 0, 8, {})])])
    out = wire.decode(wire.encode(resp))
    assert out == resp


def test_wire_rejects_unregistered():
    class Foo:
        pass
    with pytest.raises(wire.WireError):
        wire.encode(Foo())


# ---------------------------------------------------------------------------
# full cluster
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    metad = serve_metad()
    s1 = serve_storaged(metad.addr, load_interval=0.1)
    s2 = serve_storaged(metad.addr, load_interval=0.1)
    graphd = serve_graphd(metad.addr)
    yield metad, [s1, s2], graphd
    for h in (graphd, s1, s2, metad):
        h.stop()


def _wait(cond, timeout=5.0, msg="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


def test_cluster_end_to_end(cluster):
    metad, storageds, graphd = cluster
    client = GraphClient(graphd.addr).connect()

    r = client.execute("SHOW HOSTS")
    assert r.ok(), r.error_msg
    online = {row[0] for row in r.rows if row[1] == "online"}
    assert {s.addr for s in storageds} <= online

    r = client.execute("CREATE SPACE net(partition_num=4, replica_factor=1)")
    assert r.ok(), r.error_msg
    space_id = metad.meta.get_space("net").value().space_id
    # parts spread over both storageds via the topology watch
    _wait(lambda: sum(len(s.store.parts(space_id)) for s in storageds) == 4,
          msg="part sync")
    assert all(s.store.parts(space_id) for s in storageds)

    for q in ["USE net", "CREATE TAG person(name string, age int)",
              "CREATE EDGE knows(w int)"]:
        r = client.execute(q)
        assert r.ok(), (q, r.error_msg)
    r = client.execute(
        'INSERT VERTEX person(name, age) VALUES '
        '1:("a", 10), 2:("b", 20), 3:("c", 30), 4:("d", 40)')
    assert r.ok(), r.error_msg
    r = client.execute(
        "INSERT EDGE knows(w) VALUES 1->2:(12), 2->3:(23), 3->4:(34)")
    assert r.ok(), r.error_msg

    r = client.execute("GO 2 STEPS FROM 1 OVER knows YIELD knows._dst")
    assert r.ok(), r.error_msg
    assert [row[0] for row in r.rows] == [3]

    r = client.execute("GO FROM 2 OVER knows WHERE knows.w > 20 "
                       "YIELD knows._dst, $^.person.name")
    assert r.rows == [(3, "b")], r.rows

    r = client.execute("FETCH PROP ON person 3 YIELD person.name, person.age")
    assert r.rows[0][1:] == ("c", 30)

    r = client.execute('UPDATE VERTEX 3 SET person.age = $^.person.age + 1 '
                       'YIELD $^.person.age AS age')
    assert r.ok(), r.error_msg
    assert r.rows[0][0] == 31

    r = client.execute("DELETE EDGE knows 2->3")
    assert r.ok(), r.error_msg
    r = client.execute("GO FROM 2 OVER knows YIELD knows._dst")
    assert r.rows == []

    client.disconnect()


def test_bad_auth(cluster):
    _, _, graphd = cluster
    from nebula_tpu.common.status import NebulaError
    with pytest.raises(NebulaError):
        GraphClient(graphd.addr).connect("root", "wrong")


def test_session_required(cluster):
    _, _, graphd = cluster
    r = GraphClient(graphd.addr).execute("SHOW SPACES")
    assert not r.ok()
    assert r.code == ErrorCode.E_SESSION_INVALID


def test_second_graphd_same_meta(cluster):
    """A second stateless graphd sees the same catalog + data."""
    metad, _, _ = cluster
    g2 = serve_graphd(metad.addr)
    try:
        c = GraphClient(g2.addr).connect()
        r = c.execute("USE net")
        assert r.ok(), r.error_msg
        r = c.execute("FETCH PROP ON person 1 YIELD person.name")
        assert r.rows[0][1] == "a"
    finally:
        g2.stop()


# ---------------------------------------------------------------------------
# raft replication over real TCP (RpcTransport — the port+1 raft servers)
# ---------------------------------------------------------------------------

def test_replicated_cluster_failover(tmp_path):
    """3 replicated storaged over TCP raft: writes survive killing the
    leader replica (ref: parallel-raft failover + client E_LEADER_CHANGED
    retry, storage/client/StorageClient.inl:119-134)."""
    metad = serve_metad()
    storers = [serve_storaged(metad.addr, replicated=True,
                              data_dir=str(tmp_path / f"s{i}"))
               for i in range(3)]
    graphd = serve_graphd(metad.addr)
    gc = GraphClient(graphd.addr).connect()
    try:
        for s in ("CREATE SPACE rf(partition_num=2, replica_factor=3)",
                  "USE rf", "CREATE TAG t(x int)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        deadline = time.time() + 10
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX t(x) VALUES 1:(10)")
            if r.ok():
                break
            time.sleep(0.2)  # raft elections in progress
        assert r.ok(), r.error_msg
        r = gc.execute("FETCH PROP ON t 1 YIELD t.x")
        assert r.ok() and r.rows[0][-1] == 10

        # find and kill the replica leading part of vid 2's partition
        space_id = metad.meta.get_space("rf").value().space_id
        from nebula_tpu.common import keys as ku
        part = ku.part_id(2, 2)
        leader_idx = None
        deadline = time.time() + 10
        while leader_idx is None and time.time() < deadline:
            for i, h in enumerate(storers):
                raft = h.node.raft(space_id, part)
                if raft is not None and raft.is_leader():
                    leader_idx = i
            if leader_idx is None:
                time.sleep(0.1)   # this part's election still running
        assert leader_idx is not None
        storers[leader_idx].stop()

        # the client must fail over to the new leader and keep serving
        deadline = time.time() + 15
        ok = False
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX t(x) VALUES 2:(20)")
            if r.ok():
                ok = True
                break
            time.sleep(0.25)
        assert ok, f"no failover: {r.error_msg}"
        r = gc.execute("FETCH PROP ON t 2 YIELD t.x")
        assert r.ok() and r.rows[0][-1] == 20
    finally:
        graphd.stop()
        for i, h in enumerate(storers):
            if i != (leader_idx if 'leader_idx' in dir() else -1):
                try:
                    h.stop()
                except Exception:
                    pass
        metad.stop()


def test_balance_data_over_network(tmp_path):
    """BALANCE DATA in a deployed cluster: graphd forwards to the
    metad-hosted balancer, which moves parts onto a newly joined
    storaged through the storage admin RPC services (ref: Balancer +
    AdminClient + storaged AdminProcessor)."""
    metad = serve_metad()
    s0 = serve_storaged(metad.addr, replicated=True,
                        data_dir=str(tmp_path / "s0"))
    graphd = serve_graphd(metad.addr)
    gc = GraphClient(graphd.addr).connect()
    s1 = None
    try:
        for stmt in ("CREATE SPACE bal(partition_num=4, replica_factor=1)",
                     "USE bal", "CREATE TAG t(x int)"):
            r = gc.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        deadline = time.time() + 10
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX t(x) VALUES 1:(1), 2:(2), 3:(3), 4:(4)")
            if r.ok():
                break
            time.sleep(0.2)
        assert r.ok(), r.error_msg

        space_id = metad.meta.get_space("bal").value().space_id
        assert all(h == [s0.addr] for h in
                   metad.meta.get_parts_alloc(space_id).values())

        # a second storaged joins; BALANCE DATA spreads parts onto it
        s1 = serve_storaged(metad.addr, replicated=True,
                            data_dir=str(tmp_path / "s1"))
        time.sleep(0.3)   # let its heartbeat register
        r = gc.execute("BALANCE DATA")
        assert r.ok(), r.error_msg
        metad.meta._balancer.wait(30)
        alloc = metad.meta.get_parts_alloc(space_id)
        on_s1 = [p for p, hosts in alloc.items() if s1.addr in hosts]
        assert len(on_s1) == 2, alloc  # 4 parts -> 2 each

        # every task reached SUCCEEDED in the persisted plan
        tasks = metad.meta.balance_show()
        assert tasks and all(t[-1] == "SUCCEEDED" for t in tasks), tasks

        # data still all reachable after the moves
        deadline = time.time() + 10
        while time.time() < deadline:
            r = gc.execute("FETCH PROP ON t 1,2,3,4 YIELD t.x")
            if r.ok() and len(r.rows) == 4:
                break
            time.sleep(0.25)
        assert r.ok() and sorted(x[-1] for x in r.rows) == [1, 2, 3, 4], \
            (r.rows, r.error_msg)
    finally:
        graphd.stop()
        s0.stop()
        if s1 is not None:
            s1.stop()
        metad.stop()


def test_balance_refused_on_non_replicated_cluster():
    """BALANCE DATA on a non-replicated cluster fails loudly instead of
    returning a plan whose tasks all fail asynchronously."""
    metad = serve_metad()
    s0 = serve_storaged(metad.addr)   # no --replicated: no admin service
    graphd = serve_graphd(metad.addr)
    gc = GraphClient(graphd.addr).connect()
    try:
        r = gc.execute("CREATE SPACE nb(partition_num=2)")
        assert r.ok(), r.error_msg
        r = gc.execute("BALANCE DATA")
        assert not r.ok()
        assert "replicated" in r.error_msg or "admin" in r.error_msg
    finally:
        graphd.stop(); s0.stop(); metad.stop()


# ---------------------------------------------------------------------------
# transport deadlines + cluster-id enforcement (advisor findings)
# ---------------------------------------------------------------------------

def test_per_call_timeout_independent_of_pool():
    """A black-holed peer must cost <= the CALLER's timeout even when a
    long-timeout client created the address's connection pool first
    (previously the pool pinned the first client's deadline)."""
    import socket
    import threading

    from nebula_tpu.rpc import proxy
    from nebula_tpu.rpc.transport import RpcError

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(8)
    addr = f"127.0.0.1:{lst.getsockname()[1]}"
    accepted = []

    def accept_loop():
        try:
            while True:
                c, _ = lst.accept()
                accepted.append(c)   # accept, never respond
        except OSError:
            pass

    threading.Thread(target=accept_loop, daemon=True).start()
    try:
        proxy(addr, "svc", timeout=30.0)          # creates the pool
        fast = proxy(addr, "svc", timeout=0.5)
        t0 = time.time()
        with pytest.raises(RpcError):
            fast.ping()
        elapsed = time.time() - t0
        assert elapsed < 2.0, f"timeout not per-call: took {elapsed:.1f}s"
    finally:
        lst.close()
        for c in accepted:
            c.close()


def test_wrong_cluster_storaged_refuses_traffic(tmp_path):
    """A storaged pointed at a metad from a different cluster must stop
    serving (the reference daemon aborts; ref HBProcessor clusterId
    check), not keep serving traffic while invisible to liveness."""
    from nebula_tpu.rpc import proxy
    from nebula_tpu.rpc.transport import RpcError

    metad = serve_metad()
    cid_file = tmp_path / "cluster.id"
    cid_file.write_text(str(metad.meta.cluster_id + 1))  # stale/foreign id
    s = serve_storaged(metad.addr, cluster_id_file=str(cid_file),
                       load_interval=0.1)
    try:
        _wait(lambda: s.meta_client.wrong_cluster,
              msg="wrong-cluster detection")
        _wait(lambda: s.server._stopping, msg="rpc server refusing traffic")
        client = proxy(s.addr, "storage", timeout=1.0, max_attempts=2)
        with pytest.raises(RpcError):
            client.space_version(1)
    finally:
        s.stop()
        metad.stop()


# ---------------------------------------------------------------------------
# pooled client sessions (client/pool.py — the Java-client pool role)
# ---------------------------------------------------------------------------

def test_pool_session_round_robin_and_reconnect(cluster):
    from nebula_tpu.client.pool import ConnectionPool

    metad, _, _ = cluster
    # dedicated graphds — the reconnect half kills one of them, and the
    # module-scoped fixture daemon must stay up for later tests
    graphd = serve_graphd(metad.addr)
    g2 = serve_graphd(metad.addr)
    try:
        pool = ConnectionPool([graphd.addr, g2.addr], retry_after=0.2)
        with pool.session() as s:
            assert s.must("SHOW SPACES").code.name == "SUCCEEDED"
            s.must("CREATE SPACE IF NOT EXISTS poolsp(partition_num=2)")
            s.must("USE poolsp")
            s.must("CREATE TAG IF NOT EXISTS t(x int)")
            # sessions from the pool round-robin across endpoints
            with pool.session() as s2:
                assert s2.ping()
                assert s2._ep.addr != s._ep.addr
            # kill THIS session's endpoint: a mid-flight MUTATION is
            # not auto-retried (the server may have applied it before
            # the connection died — at-least-once hazard), so the
            # transport error surfaces to the caller...
            dead = s._ep.addr
            (graphd if s._ep.addr == graphd.addr else g2).stop()
            with pytest.raises(Exception):
                s.execute('INSERT VERTEX t(x) VALUES 1:(10)')
            # ...while a READ re-authenticates against the surviving
            # endpoint and retries transparently, restoring the
            # working space (USE is replayed on reconnect)
            assert s.must("SHOW SPACES").code.name == "SUCCEEDED"
            assert s._ep.addr != dead
            # the caller owns the mutation retry decision
            s.must('INSERT VERTEX t(x) VALUES 1:(10)')
            assert s.must("FETCH PROP ON t 1").rows
    finally:
        for h in (graphd, g2):
            try:
                h.stop()
            except Exception:
                pass


def test_pool_no_healthy_endpoint():
    from nebula_tpu.client.pool import ConnectionPool, NoHealthyGraphd

    pool = ConnectionPool(["127.0.0.1:1", "127.0.0.1:2"], timeout=0.5,
                          retry_after=0.1)
    with pytest.raises(NoHealthyGraphd):
        pool.session()


def test_pool_bad_credentials(cluster):
    from nebula_tpu.client.pool import ConnectionPool
    from nebula_tpu.common.status import NebulaError

    _, _, graphd = cluster
    pool = ConnectionPool([graphd.addr])
    with pytest.raises(NebulaError):
        pool.session("root", "wrong-password")


def test_storaged_advertise_host(cluster):
    """Binding a wildcard address must not leak 0.0.0.0 into the meta
    registry: --advertise-host overrides the registered address while
    the bind address keeps serving (the container deployment shape)."""
    metad, _, _ = cluster
    h = serve_storaged(metad.addr, host="0.0.0.0", load_interval=0.1,
                       advertise_host="127.0.0.1")
    try:
        port = int(h.addr.rsplit(":", 1)[1])
        _wait(lambda: f"127.0.0.1:{port}" in
              {hi.host for hi in metad.meta.active_hosts()},
              msg="advertised host registration")
        hosts = {hi.host for hi in metad.meta.active_hosts()}
        assert not any(a.startswith("0.0.0.0") for a in hosts), hosts
    finally:
        h.stop()


def test_engine_options_hot_set_via_update_configs():
    """UPDATE CONFIGS STORAGE:kv_engine_options on a graphd reaches the
    storaged's native engines within a heartbeat: set_config in the
    meta registry -> MetaClient hb pull -> flag watcher ->
    GraphStore.apply_engine_options -> nkv_set_option. Observed by the
    engine's flush threshold changing and writes freezing into runs
    (ref role: nested rocksdb option maps applied at runtime,
    RocksEngineConfig.cpp / MetaClient.cpp:1294-1429)."""
    from nebula_tpu import native
    if not native.available():
        pytest.skip("native lib not built")
    from nebula_tpu.common.flags import storage_flags
    old_hb = storage_flags.get("heartbeat_interval_secs")
    storage_flags.set("heartbeat_interval_secs", 0.2)
    metad = serve_metad()
    sd = serve_storaged(metad.addr, load_interval=0.1)
    graphd = serve_graphd(metad.addr)
    try:
        client = GraphClient(graphd.addr).connect()
        r = client.execute("CREATE SPACE cfg_sp(partition_num=2)")
        assert r.ok(), r.error_msg
        space_id = metad.meta.get_space("cfg_sp").value().space_id
        _wait(lambda: sd.store.space_engine(space_id) is not None,
              msg="space engine created")
        eng = sd.store.space_engine(space_id)
        assert eng.get_option("flush_bytes") == 64 << 20
        r = client.execute(
            "UPDATE CONFIGS STORAGE:kv_engine_options = "
            "'{\"flush_bytes\": 4096, \"max_runs\": 2}'")
        assert r.ok(), r.error_msg
        _wait(lambda: eng.get_option("flush_bytes") == 4096, timeout=10,
              msg="hot-set option to reach the engine via heartbeat")
        assert eng.get_option("max_runs") == 2
        # the tuned threshold takes effect: bulk writes freeze runs
        r = client.execute("USE cfg_sp")
        assert r.ok()
        client.execute("CREATE TAG cfg_t(x string)")
        _wait(lambda: client.execute(
            'INSERT VERTEX cfg_t(x) VALUES 1:("seed")').ok(),
            msg="schema visible to storaged")
        big = "v" * 200
        for i in range(2, 60):
            r = client.execute(
                f'INSERT VERTEX cfg_t(x) VALUES {i}:("{big}")')
            assert r.ok(), r.error_msg
        assert eng.run_count() >= 1
        # a space created AFTER the hot-set inherits the options
        r = client.execute("CREATE SPACE cfg_sp2(partition_num=1)")
        assert r.ok()
        sid2 = metad.meta.get_space("cfg_sp2").value().space_id
        _wait(lambda: sd.store.space_engine(sid2) is not None,
              msg="second space engine")
        assert sd.store.space_engine(sid2).get_option("flush_bytes") == 4096
    finally:
        storage_flags.set("heartbeat_interval_secs", old_hb)
        storage_flags.set("kv_engine_options", "")
        metad.meta.set_config("STORAGE", "kv_engine_options", "")
        for h in (graphd, sd, metad):
            h.stop()


def test_cpp_client_speaks_the_wire():
    """A SECOND-LANGUAGE client (native/client/nebula_cli.cc, C++ —
    the reference's Java-client role) authenticates, runs nGQL and
    decodes ExecutionResponse over the frozen v1 wire protocol
    against a live graphd; plus codec conformance on the spec
    vectors."""
    import json as _json
    import os
    import subprocess

    root = os.path.join(os.path.dirname(__file__), "..")
    cli = os.path.join(root, "native", "build", "nebula_cli")
    if not os.path.exists(cli):
        pytest.skip("nebula_cli not built (make -C native cli)")
    vec = os.path.join(root, "docs", "manual", "wire-vectors.json")
    out = subprocess.run([cli, "--selftest", vec], capture_output=True,
                         text=True, timeout=30)
    assert out.returncode == 0, out.stderr
    assert _json.loads(out.stdout)["vectors"] >= 23

    # own mini-cluster: the module fixture's metad has seen dead hosts
    # from other tests (e.g. the advertise-host one) that stay inside
    # the liveness horizon and could receive this space's parts
    metad = serve_metad()
    sd = serve_storaged(metad.addr, load_interval=0.1)
    graphd = serve_graphd(metad.addr)
    try:
        gc = GraphClient(graphd.addr).connect()
        for stmt in ("CREATE SPACE cpp_sp(partition_num=2)",
                     "USE cpp_sp",
                     "CREATE TAG cperson(name string)",
                     "CREATE EDGE cknows(w int)"):
            r = gc.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        # first write settles once the topology watch has the parts
        _wait(lambda: gc.execute(
            'INSERT VERTEX cperson(name) VALUES 1:("a"), 2:("b")').ok(),
            timeout=20, msg="parts ready for cpp_sp")
        r = gc.execute("INSERT EDGE cknows(w) VALUES 1 -> 2:(12)")
        assert r.ok(), r.error_msg
        out = subprocess.run(
            [cli, "--addr", graphd.addr, "--space", "cpp_sp",
             "GO FROM 1 OVER cknows YIELD cknows._dst, $^.cperson.name"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, (out.stdout, out.stderr)
        resp = _json.loads(out.stdout)
        assert resp["code"] == 0 and resp["columns"]
        assert [2, "a"] in resp["rows"], resp
        # errors surface with the server's code/message
        out = subprocess.run(
            [cli, "--addr", graphd.addr, "--space", "cpp_sp",
             "GO SYNTAX !!"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 1
        assert _json.loads(out.stdout)["code"] != 0
    finally:
        for h in (graphd, sd, metad):
            h.stop()


def test_unreplicated_storaged_survives_restart(tmp_path):
    """Clean-shutdown durability of the unreplicated native-engine
    storaged: stop() flushes every engine's memtable (nkv_close final
    run; the RocksEngine role closes through RocksDB's WAL) and a
    restart on the same --data_dir and port serves the data."""
    import socket

    from nebula_tpu import native
    if not native.available():
        pytest.skip("native lib not built")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    metad = serve_metad()
    sd = serve_storaged(metad.addr, port=port, load_interval=0.1,
                        data_dir=str(tmp_path))
    graphd = serve_graphd(metad.addr)
    sd2 = None
    try:
        gc = GraphClient(graphd.addr).connect()
        for stmt in ("CREATE SPACE persp(partition_num=2)", "USE persp",
                     "CREATE TAG t(x int)"):
            assert gc.execute(stmt).ok()
        _wait(lambda: gc.execute(
            "INSERT VERTEX t(x) VALUES 1:(10), 2:(20)").ok(),
            timeout=15, msg="first write")
        sd.stop()
        sd2 = serve_storaged(metad.addr, port=port, load_interval=0.1,
                             data_dir=str(tmp_path))
        rows = []

        def fetched():
            nonlocal rows
            r = gc.execute("FETCH PROP ON t 1 YIELD t.x")
            rows = r.rows if r.ok() else []
            return bool(rows)
        _wait(fetched, timeout=15, msg="data after restart")
        assert rows[0][-1] == 10
    finally:
        for h in (graphd, sd2 or sd, metad):
            try:
                h.stop()
            except Exception:
                pass


def test_retry_safe_compound_statements():
    """Advisor finding (round 4, pool.py:141): classification must
    cover every `;`-segment, not just the first token — a compound
    carrying a mutation is NOT auto-retried, while `;` inside string
    literals never splits."""
    from nebula_tpu.client.pool import Session as S

    assert S._retry_safe("GO FROM 1 OVER e")
    assert S._retry_safe("USE x; SHOW TAGS; GO FROM 1 OVER e")
    assert S._retry_safe("$a = GO FROM 1 OVER e; YIELD $a.x")
    assert not S._retry_safe("USE x; INSERT VERTEX t(x) VALUES 1:(1)")
    assert not S._retry_safe("$a = GO FROM 1 OVER e; DELETE VERTEX 1")
    assert not S._retry_safe("$a = INSERT VERTEX t(x) VALUES 1:(1)")
    assert not S._retry_safe("$a.b")            # not an assignment
    # a quoted semicolon + mutation keyword stays ONE read statement
    assert S._retry_safe('LOOKUP ON t WHERE t.s == "a;DELETE VERTEX 1"')
    assert not S._retry_safe("UPDATE VERTEX 1 SET t.x = 1")


def test_tpu_served_across_replica_failover(tmp_path):
    """Device-served GO across a storaged leader kill: the freshness
    token carries part->leader routing, so the failover invalidates
    the snapshot (token incompatible -> rebuild from the NEW leaders)
    and the engine must re-serve on device with identical results —
    degrading to the CPU fan-out only while the topology settles."""
    from nebula_tpu.common import keys as ku
    from nebula_tpu.engine_tpu import TpuGraphEngine

    metad = serve_metad()
    storers = [serve_storaged(metad.addr, replicated=True,
                              data_dir=str(tmp_path / f"s{i}"))
               for i in range(3)]
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu)
    gc = GraphClient(graphd.addr).connect()
    try:
        for s in ("CREATE SPACE rft(partition_num=2, replica_factor=3)",
                  "USE rft", "CREATE TAG person(age int)",
                  "CREATE EDGE knows(w int)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        deadline = time.time() + 15
        while time.time() < deadline:
            r = gc.execute("INSERT VERTEX person(age) VALUES "
                           "1:(10), 2:(20), 3:(30), 4:(40)")
            if r.ok():
                break
            time.sleep(0.2)   # raft elections in progress
        assert r.ok(), r.error_msg
        r = gc.execute("INSERT EDGE knows(w) VALUES 1 -> 2:(5), "
                       "2 -> 3:(6), 1 -> 3:(7), 3 -> 4:(8)")
        assert r.ok(), r.error_msg
        q = "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst"
        want = [(3,), (4,)]

        def device_served():
            before = tpu.stats["go_served"]
            r = gc.execute(q)
            assert r.ok(), r.error_msg
            return (sorted(r.rows), tpu.stats["go_served"] > before)

        deadline = time.time() + 20
        while time.time() < deadline:
            rows, on_device = device_served()
            if on_device and rows == want:
                break
            # watch channels still priming, or a bounded-staleness
            # follower read served before the inserts applied there
            time.sleep(0.3)
        assert on_device and rows == want, (rows, tpu.stats)

        # kill the leader of vid 1's part; meta moves leadership to a
        # survivor and the engine must rebuild from the new routing
        space_id = metad.meta.get_space("rft").value().space_id
        part = ku.part_id(1, 2)
        leader_idx = None
        deadline = time.time() + 10
        while leader_idx is None and time.time() < deadline:
            for i, h in enumerate(storers):
                raft = h.node.raft(space_id, part)
                if raft is not None and raft.is_leader():
                    leader_idx = i
            if leader_idx is None:
                time.sleep(0.1)
        assert leader_idx is not None
        storers[leader_idx].stop()

        deadline = time.time() + 30
        on_device = False
        while time.time() < deadline:
            try:
                rows, on_device = device_served()
            except AssertionError:
                time.sleep(0.3)   # elections / topology settling
                continue
            if on_device and rows == want:
                break
            time.sleep(0.3)
        assert on_device and rows == want, (rows, tpu.stats)
    finally:
        graphd.stop()
        for h in storers:
            try:
                h.stop()
            except Exception:
                pass
        metad.stop()


def test_tpu_concurrent_identity_over_tcp_native():
    """Concurrency soak over the REAL topology: native-engine storaged,
    --tpu graphd, concurrent TCP writers + readers (dispatcher rounds,
    delta pulls resolving against the C++ engine under live writes),
    then a quiesced CPU/TPU identity sweep. Exercises the native
    changelog + remote snapshot provider under the interleavings the
    in-proc soak can't."""
    import threading

    import numpy as np
    from nebula_tpu import native as native_mod
    from nebula_tpu.engine_tpu import TpuGraphEngine

    if not native_mod.available():
        pytest.skip("native library unavailable")
    metad = serve_metad()
    sd = serve_storaged(metad.addr, load_interval=0.1)
    tpu = TpuGraphEngine()
    graphd = serve_graphd(metad.addr, tpu_engine=tpu)
    v, e = 600, 3000
    try:
        gc = GraphClient(graphd.addr).connect()
        for s in ("CREATE SPACE tsoak(partition_num=4)", "USE tsoak",
                  "CREATE TAG person(age int)", "CREATE EDGE knows(w int)"):
            r = gc.execute(s)
            assert r.ok(), (s, r.error_msg)
        rng = np.random.default_rng(23)
        srcs = rng.integers(0, v, e)
        dsts = rng.integers(0, v, e)
        gc.execute("INSERT VERTEX person(age) VALUES " + ", ".join(
            f"{j}:({j % 70})" for j in range(v)))
        for i in range(0, e, 1500):
            r = gc.execute("INSERT EDGE knows(w) VALUES " + ", ".join(
                f"{int(s)} -> {int(d)}:({int((s + d) % 101)})"
                for s, d in zip(srcs[i:i + 1500], dsts[i:i + 1500])))
            assert r.ok(), r.error_msg
        gc.execute("GO FROM 0 OVER knows")
        hubs = [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=v))[-3:]]
        errors = []
        stop = threading.Event()

        def reader(k):
            import random as _r
            rr = _r.Random(k)
            c = GraphClient(graphd.addr).connect()
            c.execute("USE tsoak")
            while not stop.is_set():
                h = rr.choice(hubs)
                r = c.execute(f"GO 2 STEPS FROM {h} OVER knows "
                              f"YIELD knows._dst, knows.w")
                if not r.ok():
                    errors.append(r.error_msg)
                    return

        def writer(k):
            import random as _r
            import time as _t
            rr = _r.Random(900 + k)
            c = GraphClient(graphd.addr).connect()
            c.execute("USE tsoak")
            while not stop.is_set():
                s, d = rr.randrange(v), rr.randrange(v)
                if rr.random() < 0.8:
                    r = c.execute(f"INSERT EDGE knows(w) VALUES "
                                  f"{s} -> {d}:({(s + d) % 101})")
                else:
                    r = c.execute(f"DELETE EDGE knows {s} -> {d}")
                if not r.ok():
                    errors.append(r.error_msg)
                    return
                _t.sleep(0.002)

        ts = [threading.Thread(target=writer, args=(i,)) for i in range(2)]
        ts += [threading.Thread(target=reader, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        time.sleep(4.0)
        stop.set()
        for t in ts:
            t.join(timeout=30)
        assert not [t for t in ts if t.is_alive()], "stragglers"
        assert not errors, errors[:3]
        # quiesce background repacks, then identity-sweep
        deadline = time.time() + 10
        while any(tpu._repacking.values()) and time.time() < deadline:
            time.sleep(0.02)
        for q in ([f"GO 2 STEPS FROM {h} OVER knows "
                   f"YIELD knows._dst, knows.w" for h in hubs]
                  + [f"GO FROM {hubs[0]}, {hubs[1]} OVER knows YIELD "
                     f"knows.w AS w | YIELD COUNT(*) AS n, SUM($-.w)"
                     f" AS s"]):
            rt = gc.execute(q)
            assert rt.ok(), rt.error_msg
            tpu.enabled = False
            try:
                rc = gc.execute(q)
            finally:
                tpu.enabled = True
            assert rc.ok(), rc.error_msg
            assert sorted(map(repr, rt.rows)) == \
                sorted(map(repr, rc.rows)), q
        assert tpu.stats["go_served"] > 0, tpu.stats
    finally:
        graphd.stop(); sd.stop(); metad.stop()


def test_dedicated_client_close_then_reconnect_fast():
    """Satellite (ISSUE 1): _ConnPool.close() frees creation slots per
    drained socket — a reused dedicated client (disconnect ->
    reconnect) dials a fresh connection immediately instead of
    blocking the full acquire timeout and raising RpcError 'no pooled
    connection'."""
    from nebula_tpu.rpc.transport import RpcServer, proxy

    class Echo:
        def ping(self):
            return "pong"

    srv = RpcServer().register("echo", Echo()).start()
    try:
        c = proxy(srv.addr, "echo", timeout=3.0, dedicated=True)
        assert c.ping() == "pong"
        c.close()                       # disconnect
        t0 = time.time()
        assert c.ping() == "pong"       # reconnect must not block 3s
        assert time.time() - t0 < 1.5, "close() leaked a creation slot"
        c.close()
    finally:
        srv.stop()


def test_storage_client_hintless_retry_during_election():
    """Satellite (ISSUE 6): an in-flight election answers
    E_LEADER_CHANGED with NO leader hint — the client must rotate
    hosts with bounded backoff until a leader emerges, counting the
    rounds, instead of surfacing an error."""
    from nebula_tpu.storage.client import StorageClient
    from nebula_tpu.storage.types import PropsResponse, VertexData

    class FakeSM:
        def num_parts(self, space_id):
            return 1

    class ElectingService:
        """Hintless E_LEADER_CHANGED for the first `n` calls (an
        election in flight), then serves."""

        def __init__(self, n):
            self.remaining = n
            self.calls = 0

        def get_vertex_props(self, space_id, parts, tag_ids):
            self.calls += 1
            r = PropsResponse()
            if self.remaining > 0:
                self.remaining -= 1
                for p in parts:
                    r.results[p] = PartResult(
                        ErrorCode.E_LEADER_CHANGED, None)
            else:
                for p in parts:
                    r.results[p] = PartResult()
                r.vertices.append(VertexData(1, {}, []))
            return r

    svc = ElectingService(3)
    client = StorageClient(FakeSM(), hosts={"h0": svc, "h1": svc},
                           part_to_host=lambda s, p: "h0")
    t0 = time.time()
    resp = client.get_vertex_props(1, [1])
    assert resp.results[1].code == ErrorCode.SUCCEEDED, resp.results
    assert resp.vertices, "election never resolved into a served read"
    assert client.retry_stats["hintless"] >= 3, client.retry_stats
    # bounded jittered backoff, not a spin: 3 hintless rounds must
    # take measurable-but-small wall time
    assert 0.01 < time.time() - t0 < 10


def test_storage_client_dead_host_rotation_counts():
    """A host that dies mid-request (transport exception) is treated
    as a hintless election: rotate to a replica, count the round."""
    from nebula_tpu.storage.client import StorageClient
    from nebula_tpu.storage.types import PropsResponse, VertexData

    class FakeSM:
        def num_parts(self, space_id):
            return 1

    class DeadService:
        def get_vertex_props(self, *a):
            raise ConnectionError("connection refused")

    class LiveService:
        def get_vertex_props(self, space_id, parts, tag_ids):
            r = PropsResponse()
            for p in parts:
                r.results[p] = PartResult()
            r.vertices.append(VertexData(2, {}, []))
            return r

    client = StorageClient(FakeSM(),
                           hosts={"dead": DeadService(),
                                  "live": LiveService()},
                           part_to_host=lambda s, p: "dead")
    resp = client.get_vertex_props(1, [2])
    assert resp.results[1].code == ErrorCode.SUCCEEDED
    assert client.retry_stats["hintless"] >= 1
    # the rotation stuck: the leader cache now routes to the survivor
    assert client._leader(1, 1) == "live"


def test_replica_reconcile_late_joining_storaged(tmp_path):
    """Satellite (ISSUE 6): CREATE SPACE replica_factor=3 with only two
    live storaged must succeed under-replicated, and a LATE-JOINING
    storaged is reconciled in via its heartbeat: metad tops the part
    allocation up to replica_factor, the new host materializes the
    parts as learners, and the incumbent raft leaders admit it via
    ADD_PEER — ending fully replicated with the data caught up."""
    from nebula_tpu.common.flags import storage_flags
    from nebula_tpu.meta.net_admin import raft_addr_of

    old_hb = storage_flags.get("heartbeat_interval_secs")
    storage_flags.set("heartbeat_interval_secs", 0.3)
    metad = serve_metad()
    storers = [serve_storaged(metad.addr, replicated=True,
                              data_dir=str(tmp_path / f"s{i}"),
                              load_interval=0.1)
               for i in range(2)]
    graphd = serve_graphd(metad.addr)
    gc = GraphClient(graphd.addr).connect()
    late = None
    try:
        r = gc.execute(
            "CREATE SPACE lj(partition_num=2, replica_factor=3)")
        assert r.ok(), r.error_msg      # under-provisioned is ACCEPTED
        gc.must("USE lj")
        gc.must("CREATE TAG t(x int)")
        space_id = metad.meta.get_space("lj").value().space_id
        alloc = metad.meta.get_parts_alloc(space_id)
        assert all(len(hosts) == 2 for hosts in alloc.values()), alloc
        _wait(lambda: gc.execute(
            "INSERT VERTEX t(x) VALUES 1:(10), 2:(20), 3:(30)").ok(),
            timeout=15, msg="first write (elections)")

        # the third storaged joins late: heartbeat reconcile must top
        # every part up to replica_factor=3 with it
        from nebula_tpu.common.stats import stats as gstats
        reconciled0 = gstats.lifetime_total(
            "raftex.membership_reconciled")
        late = serve_storaged(metad.addr, replicated=True,
                              data_dir=str(tmp_path / "s2"),
                              load_interval=0.1)
        _wait(lambda: all(late.addr in hosts and len(hosts) == 3
                          for hosts in metad.meta.get_parts_alloc(
                              space_id).values()),
              timeout=15, msg="allocation topped up to replica_factor")

        # raft side: the late replica is admitted as a peer (promoted
        # from learner by the leader's membership reconcile) and
        # catches the data up
        def caught_up():
            for p in (1, 2):
                r_late = late.node.raft(space_id, p)
                if r_late is None or r_late.role.name == "LEARNER":
                    return False
                lead = None
                for h in storers:
                    rp = h.node.raft(space_id, p)
                    if rp is not None and rp.is_leader():
                        lead = rp
                if lead is None:
                    return False
                if raft_addr_of(late.addr) not in lead.peers:
                    return False
                if r_late.committed_id < lead.committed_id:
                    return False
            return True

        _wait(caught_up, timeout=20, msg="late replica admitted + caught up")
        # the join went through the designed path: the incumbent
        # leaders ADMITTED the newcomer via membership reconcile
        # (an empty-log voter sneaking in via elections would leave
        # this counter untouched)
        assert gstats.lifetime_total("raftex.membership_reconciled") \
            > reconciled0

        # the leader view reaches SHOW PARTS within a heartbeat
        def leaders_shown():
            r = gc.execute("SHOW PARTS")
            if not r.ok() or len(r.rows) != 2:
                return False
            return all(row[1] for row in r.rows)
        _wait(leaders_shown, timeout=15, msg="SHOW PARTS leader column")
        r = gc.must("SHOW HOSTS")
        assert r.columns[2] == "Leader count"
        assert sum(row[2] for row in r.rows) >= 2, r.rows
    finally:
        storage_flags.set("heartbeat_interval_secs", old_hb)
        gc.disconnect()
        graphd.stop()
        for h in storers + ([late] if late else []):
            try:
                h.stop()
            except Exception:
                pass
        metad.stop()
