"""Tier-1-safe workload-observatory smoke: `bench.py --skew --trim` in
a SUBPROCESS on XLA:CPU — the Zipf workload tier that proves the
hot-vertex sketch's top-K recall against ground truth, the per-space
skew index separating uniform from Zipf runs, the hot_part flight
trigger, the heat-aware BALANCE advisor reducing modeled per-host
heat spread on a deliberately skewed layout, and the disarmed path
leaving the metrics surface untouched (docs/manual/
10-observability.md, "Workload & data observatory"). The subprocess
keeps the parent's JAX backend state out of the picture, exactly like
the chaos/cluster/qos smoke tiers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def skew_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("skew") / "SKEW_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SKEW_SEED"] = "13"       # deterministic draws/layout
    env["BENCH_SKEW_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--skew", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_skew_all_gates_green(skew_smoke):
    assert skew_smoke["ok"] is True, skew_smoke["gates"]
    assert all(skew_smoke["gates"].values()), skew_smoke["gates"]


def test_skew_sketch_recall(skew_smoke):
    sk = skew_smoke["sketch"]
    assert sk["recall"] >= 0.9, sk
    assert sk["tracked"] <= sk["k"]      # cardinality cap held
    assert set(sk["est_topk"]) & set(sk["true_topk"])


def test_skew_index_separates(skew_smoke):
    si = skew_smoke["skew_index"]
    assert si["zipf"] >= 1.5 * si["uniform"], si
    assert si["uniform"] < 1.6, si       # uniform reads near-flat
    assert si["zipf"] > 1.2, si


def test_skew_advisor_reduces_spread(skew_smoke):
    adv = skew_smoke["advisor"]
    assert adv["advisory"] is True
    assert adv["moves"], adv
    assert adv["spread_after"] < adv["spread_before"], adv


def test_skew_disarmed_and_hot_part(skew_smoke):
    d = skew_smoke["disarmed"]
    assert d["metric_lines"] == 0 and d["gauges"] == 0
    hp = skew_smoke["hot_part"]
    assert hp["bundles"] >= 1, hp
    # the tier-wide heat block landed in the artifact (the tier-2/3
    # _obs_block twin) with a populated skew map
    assert skew_smoke["heat"]["skew"], skew_smoke["heat"]
