"""SLO burn-rate engine (ISSUE 10 tentpole): plan grammar, latency/
availability burn math over the native histograms, the multi-window
breach guard, the breach -> flight-recorder loop, gauges and the /slo
surface."""
import time

import pytest

from nebula_tpu.common.flags import graph_flags
from nebula_tpu.common.flight import FlightRecorder
from nebula_tpu.common.slo import (DEFAULT_BURN_THRESHOLD, SloEngine,
                                   parse_plan)
from nebula_tpu.common.stats import StatsManager


# ------------------------------------------------------------- grammar

def test_plan_grammar_parses_both_kinds():
    objs = parse_plan(
        "lat:kind=latency,metric=graph.query_latency_us,le_ms=50,"
        "target=0.99;"
        "avail:kind=availability,good=graph.qos.admitted.t1,"
        "bad=graph.qos.denied.t1,target=0.9,burn=2")
    assert [o.name for o in objs] == ["lat", "avail"]
    assert objs[0].kind == "latency" and objs[0].le_us == 50_000
    assert objs[0].burn_threshold == DEFAULT_BURN_THRESHOLD
    assert objs[1].kind == "availability" and objs[1].burn_threshold == 2
    assert abs(objs[1].budget - 0.1) < 1e-9


@pytest.mark.parametrize("plan,needle", [
    ("x:kind=frobnicate,target=0.9", "unknown kind"),
    ("x:kind=latency,metric=m,le_ms=5", "needs kind= and target="),
    ("x:kind=latency,target=0.9", "needs metric= and le_ms="),
    ("x:kind=availability,target=0.9", "needs good= and bad="),
    ("x:kind=latency,metric=m,le_ms=5,target=1.5", "target must be"),
    ("x:kind=latency,metric=m,le_ms=5,target=0.9,burn=0", "burn must"),
    ("x:kind=latency,metric=m,le_ms=5,target=0.9,zap=1", "unknown slo"),
    ("x kind=latency", "bad slo entry"),
    ("a:kind=latency,metric=m,le_ms=5,target=0.9;"
     "a:kind=latency,metric=m,le_ms=5,target=0.9", "duplicate slo"),
])
def test_plan_grammar_rejects(plan, needle):
    with pytest.raises(ValueError) as ei:
        parse_plan(plan)
    assert needle in str(ei.value)


def test_bad_plan_keeps_previous(slo_quad):
    eng, _, _, _ = slo_quad
    eng.set_plan("ok:kind=latency,metric=m,le_ms=5,target=0.9")
    with pytest.raises(ValueError):
        eng.set_plan("broken:kind=nope,target=0.9")
    assert eng.describe()["plan"].startswith("ok:")
    eng.clear()


# ---------------------------------------------------------- evaluation

@pytest.fixture
def slo_quad():
    """(engine, stats, clock, flight) with a controllable clock and a
    private flight recorder — no process-global state touched."""
    clock = [10_000.0]
    sm = StatsManager(clock=lambda: clock[0])
    fr = FlightRecorder(ring_size=32, clock=lambda: clock[0])
    eng = SloEngine(stats=sm, flight_recorder=fr)
    yield eng, sm, clock, fr
    eng.clear()


def test_latency_burn_math_and_multiwindow_breach(slo_quad):
    eng, sm, clock, fr = slo_quad
    eng.set_plan("lat:kind=latency,metric=lat_us,le_ms=10,target=0.9,"
                 "burn=5")
    # 10 samples: 4 slow (40% bad), budget 0.1 -> burn 4.0 < 5
    for _ in range(6):
        sm.add_value("lat_us", 1_000.0, kind="histogram")
    for _ in range(4):
        sm.add_value("lat_us", 1_000_000.0, kind="histogram")
    recs = eval_one(eng)
    assert recs["windows"]["60"]["burn"] == pytest.approx(4.0)
    assert not recs["breached"] and not fr.bundles
    # 6 more slow: 10/16 bad -> burn 6.25 >= 5 on BOTH 60s and 600s
    for _ in range(6):
        sm.add_value("lat_us", 1_000_000.0, kind="histogram")
    recs = eval_one(eng)
    assert recs["breached"] and recs["breaches"] == 1
    # breach -> flight loop: the slo_burn trigger captured a bundle
    assert fr.bundles and fr.bundles[-1]["trigger"] == "slo_burn"
    assert fr.bundles[-1]["event"]["objective"] == "lat"
    # recovery: the bad samples age out of the 60s window (they stay
    # inside 600s, so the multi-window guard is what clears first on
    # the short window -> no longer "both over" -> recovered)
    clock[0] += 120
    for _ in range(50):
        sm.add_value("lat_us", 1_000.0, kind="histogram")
    recs = eval_one(eng)
    assert recs["windows"]["60"]["burn"] == 0.0
    assert not recs["breached"]
    assert recs["breaches"] == 1      # lifetime count survives


def test_availability_burn_over_qos_counters(slo_quad):
    eng, sm, clock, fr = slo_quad
    eng.set_plan("t1:kind=availability,good=qos.admitted.t1,"
                 "bad=qos.denied.t1,target=0.9,burn=2")
    for _ in range(8):
        sm.add_value("qos.admitted.t1", kind="counter")
    for _ in range(2):
        sm.add_value("qos.denied.t1", kind="counter")
    # 2/10 bad, budget 0.1 -> burn 2.0 >= 2 on both windows: breach
    recs = eval_one(eng)
    assert recs["windows"]["60"]["ratio"] == pytest.approx(0.2)
    assert recs["breached"]
    # dilution recovery: good traffic pushes the ratio under budget
    for _ in range(90):
        sm.add_value("qos.admitted.t1", kind="counter")
    recs = eval_one(eng)
    assert recs["windows"]["60"]["burn"] < 2
    assert not recs["breached"]


def test_empty_metrics_do_not_breach(slo_quad):
    eng, sm, clock, fr = slo_quad
    eng.set_plan("lat:kind=latency,metric=never_fed,le_ms=1,"
                 "target=0.999")
    recs = eval_one(eng)
    assert recs["windows"]["60"] == {"bad": 0.0, "total": 0.0,
                                     "ratio": 0.0, "burn": 0.0}
    assert not recs["breached"]


def test_gauges_shape(slo_quad):
    eng, sm, clock, fr = slo_quad
    eng.set_plan("lat:kind=latency,metric=lat_us,le_ms=10,target=0.9")
    sm.add_value("lat_us", 500.0, kind="histogram")
    g = eng.gauges()
    for key in ("slo.lat.burn_60s", "slo.lat.burn_600s",
                "slo.lat.burn_3600s", "slo.lat.breached",
                "slo.lat.breaches"):
        assert key in g
    assert g["slo.lat.breached"] == 0.0


def eval_one(eng):
    recs = eng.evaluate()
    assert len(recs) == 1
    return recs[0]


# ------------------------------------------------------- global wiring

def test_slo_plan_flag_watcher_and_bad_plan_counter():
    from nebula_tpu.common.slo import engine as global_engine
    from nebula_tpu.common.stats import stats as global_stats

    try:
        graph_flags.set("slo_plan",
                        "w:kind=latency,metric=graph.query_latency_us,"
                        "le_ms=50,target=0.99")
        assert any(o["name"] == "w"
                   for o in global_engine.describe()["objectives"])
        b0 = global_stats.lifetime_total("slo.bad_plan")
        graph_flags.set("slo_plan", "broken:kind=zap")
        # rejected: previous plan kept, evidence left
        assert global_stats.lifetime_total("slo.bad_plan") > b0
        assert any(o["name"] == "w"
                   for o in global_engine.describe()["objectives"])
    finally:
        graph_flags.set("slo_plan", "")
        global_engine.clear()


def test_slo_endpoint_put_validates_before_mutating():
    from nebula_tpu.common.slo import engine as global_engine
    from nebula_tpu.webservice import WebService

    ws = WebService("t")
    try:
        code, body = ws._slo_handler(
            {}, b"plan=e:kind=latency,metric=m,le_ms=5,target=0.9")
        assert code == 200
        assert body["objectives"][0]["name"] == "e"
        code, body = ws._slo_handler({}, b"plan=broken")
        assert code == 400 and "bad slo entry" in body["error"]
        # previous plan survived the 400
        assert global_engine.describe()["plan"].startswith("e:")
        code, body = ws._slo_handler({"clear": "1"}, b"")
        assert code == 200 and body["objectives"] == []
    finally:
        global_engine.clear()
