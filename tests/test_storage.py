"""Storage processor + client tests (parity model: storage/test/QueryBoundTest,
AddEdgesTest, UpdateVertexTest, StorageClientTest)."""
import pytest

from nebula_tpu.codec import PropType, RowWriter, Schema, SchemaField
from nebula_tpu.common.status import ErrorCode
from nebula_tpu.filter.expressions import encode_expression
from nebula_tpu.kvstore import GraphStore
from nebula_tpu.meta.schema_manager import AdHocSchemaManager
from nebula_tpu.parser import GQLParser
from nebula_tpu.storage import (EdgeKey, NewEdge, NewVertex, StorageClient,
                                StorageService, UpdateItemReq)

NUM_PARTS = 4
PLAYER_TAG = 1
LIKE_EDGE = 1
SERVE_EDGE = 2


def parse_expr(text):
    return GQLParser().parse(f"YIELD {text} AS x").sentences[0].yield_.columns[0].expr


@pytest.fixture()
def cluster():
    """In-proc single-node mini-cluster (parity: TestUtils::setupKV)."""
    sm = AdHocSchemaManager()
    sm.set_num_parts(1, NUM_PARTS)
    player = Schema([SchemaField("name", PropType.STRING),
                     SchemaField("age", PropType.INT)])
    like = Schema([SchemaField("likeness", PropType.DOUBLE)])
    serve = Schema([SchemaField("years", PropType.INT)])
    sm.add_tag(1, PLAYER_TAG, "player", player)
    sm.add_edge(1, LIKE_EDGE, "like", like)
    sm.add_edge(1, SERVE_EDGE, "serve", serve)
    store = GraphStore()
    for p in range(1, NUM_PARTS + 1):
        store.add_part(1, p)
    svc = StorageService(store, sm)
    client = StorageClient(sm, local_service=svc)
    return sm, store, svc, client, player, like, serve


def insert_sample(client, player, like, serve):
    vertices = []
    for vid, name, age in [(100, "Tim", 42), (101, "Tony", 36), (102, "Manu", 41),
                           (103, "LaMarcus", 33)]:
        row = RowWriter(player).set("name", name).set("age", age).encode()
        vertices.append(NewVertex(vid, [(PLAYER_TAG, row)]))
    assert client.add_vertices(1, vertices).ok()
    edges = []
    for src, dst, w in [(100, 101, 95.0), (100, 102, 95.0), (101, 100, 95.0),
                        (102, 100, 90.0), (103, 100, 75.0)]:
        row = RowWriter(like).set("likeness", w).encode()
        edges.append(NewEdge(src, LIKE_EDGE, 0, dst, row))
    assert client.add_edges(1, edges).ok()
    return vertices, edges


def test_get_neighbors_out(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    resp = client.get_neighbors(1, [100], [LIKE_EDGE])
    by_vid = {v.vid: v for v in resp.vertices}
    dsts = sorted(e.dst for e in by_vid[100].edges)
    assert dsts == [101, 102]
    props = {e.dst: e.props["likeness"] for e in by_vid[100].edges}
    assert props == {101: 95.0, 102: 95.0}


def test_get_neighbors_reverse(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    resp = client.get_neighbors(1, [100], [-LIKE_EDGE])
    by_vid = {v.vid: v for v in resp.vertices}
    dsts = sorted(e.dst for e in by_vid[100].edges)
    assert dsts == [101, 102, 103]  # who likes 100


def test_get_neighbors_with_src_props(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    resp = client.get_neighbors(1, [100], [LIKE_EDGE],
                                vertex_props={PLAYER_TAG: ["name"]})
    v = {v.vid: v for v in resp.vertices}[100]
    assert v.tag_props[PLAYER_TAG] == {"name": "Tim"}


def test_filter_pushdown_on_edge_props(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    flt = encode_expression(parse_expr("like.likeness > 80.0"))
    resp = client.get_neighbors(1, [103, 102], [LIKE_EDGE], filter_bytes=flt)
    edges = [e for v in resp.vertices for e in v.edges]
    # 103 -> 100 has likeness 75, filtered out; 102 -> 100 (90) kept
    assert [(e.src, e.dst) for e in edges] == [(102, 100)]


def test_filter_pushdown_on_src_props(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    flt = encode_expression(parse_expr("$^.player.age > 40"))
    resp = client.get_neighbors(1, [100, 101], [LIKE_EDGE], filter_bytes=flt)
    srcs = sorted({e.src for v in resp.vertices for e in v.edges})
    assert srcs == [100]  # Tim (42) passes, Tony (36) filtered


def test_filter_not_pushable_rejected(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    flt = encode_expression(parse_expr("$$.player.age > 40"))
    resp = client.get_neighbors(1, [100], [LIKE_EDGE], filter_bytes=flt)
    assert all(r.code == ErrorCode.E_INVALID_FILTER for r in resp.results.values())


def test_edge_version_dedup(cluster):
    """Two writes to the same logical edge: scan sees only the newest."""
    sm, store, svc, client, player, like, serve = cluster
    row1 = RowWriter(like).set("likeness", 10.0).encode()
    client.add_edges(1, [NewEdge(1, LIKE_EDGE, 0, 2, row1)])
    import time
    time.sleep(0.001)
    row2 = RowWriter(like).set("likeness", 99.0).encode()
    client.add_edges(1, [NewEdge(1, LIKE_EDGE, 0, 2, row2)])
    resp = client.get_neighbors(1, [1], [LIKE_EDGE])
    edges = [e for v in resp.vertices for e in v.edges]
    assert len(edges) == 1
    assert edges[0].props["likeness"] == 99.0


def test_max_edges_cap(cluster):
    sm, store, svc, client, player, like, serve = cluster
    rows = [NewEdge(7, LIKE_EDGE, r, 1000 + r,
                    RowWriter(like).set("likeness", 1.0).encode())
            for r in range(20)]
    client.add_edges(1, rows)
    resp = client.get_neighbors(1, [7], [LIKE_EDGE], max_edges_per_vertex=5)
    edges = [e for v in resp.vertices for e in v.edges]
    assert len(edges) == 5


def test_get_vertex_props(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    resp = client.get_vertex_props(1, [100, 101])
    by_vid = {v.vid: v for v in resp.vertices}
    assert by_vid[100].tag_props[PLAYER_TAG]["name"] == "Tim"
    assert by_vid[101].tag_props[PLAYER_TAG]["age"] == 36


def test_get_edge_props(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    resp = client.get_edge_props(1, [EdgeKey(100, LIKE_EDGE, 0, 101)])
    assert len(resp.edges) == 1
    assert resp.edges[0].props["likeness"] == 95.0


def test_delete_edges_removes_both_directions(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    client.delete_edges(1, [EdgeKey(100, LIKE_EDGE, 0, 101)])
    out = client.get_neighbors(1, [100], [LIKE_EDGE])
    assert sorted(e.dst for v in out.vertices for e in v.edges) == [102]
    rev = client.get_neighbors(1, [101], [-LIKE_EDGE])
    assert [e.dst for v in rev.vertices for e in v.edges] == []


def test_delete_vertex_cascades(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    client.delete_vertices(1, [100])
    props = client.get_vertex_props(1, [100])
    assert props.vertices == []
    # in-neighbors no longer see edges to 100
    resp = client.get_neighbors(1, [101, 102, 103], [LIKE_EDGE])
    dsts = [e.dst for v in resp.vertices for e in v.edges]
    assert 100 not in dsts


def test_update_vertex_with_when_and_yield(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    items = [UpdateItemReq("age", encode_expression(parse_expr("age + 1")))]
    resp = client.update_vertex(1, 100, PLAYER_TAG, items,
                                when=encode_expression(parse_expr("age > 40")),
                                yield_props=["age"])
    assert resp.code == ErrorCode.SUCCEEDED
    assert resp.props == {"age": 43}
    # WHEN fails for Tony (36)
    resp = client.update_vertex(1, 101, PLAYER_TAG, items,
                                when=encode_expression(parse_expr("age > 40")))
    assert resp.code == ErrorCode.E_FILTER_OUT


def test_upsert_vertex_missing(cluster):
    sm, store, svc, client, player, like, serve = cluster
    items = [UpdateItemReq("age", encode_expression(parse_expr("77")))]
    resp = client.update_vertex(1, 999, PLAYER_TAG, items, insertable=False)
    assert resp.code == ErrorCode.E_KEY_NOT_FOUND
    resp = client.update_vertex(1, 999, PLAYER_TAG, items, insertable=True,
                                yield_props=["age"])
    assert resp.code == ErrorCode.SUCCEEDED and resp.upsert
    assert resp.props == {"age": 77}


def test_update_edge_keeps_reverse_in_sync(cluster):
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    items = [UpdateItemReq("likeness", encode_expression(parse_expr("50.0")))]
    resp = client.update_edge(1, EdgeKey(100, LIKE_EDGE, 0, 101), items)
    assert resp.code == ErrorCode.SUCCEEDED
    fwd = client.get_neighbors(1, [100], [LIKE_EDGE])
    vals = {e.dst: e.props["likeness"] for v in fwd.vertices for e in v.edges}
    assert vals[101] == 50.0
    rev = client.get_neighbors(1, [101], [-LIKE_EDGE])
    vals = {e.dst: e.props["likeness"] for v in rev.vertices for e in v.edges}
    assert vals[100] == 50.0


def test_uuid_stable(cluster):
    sm, store, svc, client, player, like, serve = cluster
    _, vid1 = client.get_uuid(1, "Tim Duncan")
    _, vid2 = client.get_uuid(1, "Tim Duncan")
    _, vid3 = client.get_uuid(1, "Tony Parker")
    assert vid1 == vid2
    assert vid1 != vid3


def test_ttl_expired_rows_invisible(cluster):
    sm, store, svc, client, player, like, serve = cluster
    import time
    ttl_tag = Schema([SchemaField("v", PropType.INT),
                      SchemaField("ts", PropType.TIMESTAMP)],
                     ttl_col="ts", ttl_duration=1000)
    sm.add_tag(1, 9, "ephemeral", ttl_tag)
    now = int(time.time())
    fresh = RowWriter(ttl_tag).set("v", 1).set("ts", now).encode()
    stale = RowWriter(ttl_tag).set("v", 2).set("ts", now - 5000).encode()
    client.add_vertices(1, [NewVertex(201, [(9, fresh)]),
                            NewVertex(202, [(9, stale)])])
    resp = client.get_vertex_props(1, [201, 202], tag_ids=[9])
    vids = [v.vid for v in resp.vertices]
    assert vids == [201]


def test_bound_stats_pushdown(cluster):
    """SUM/COUNT/AVG aggregate pushdown (parity: QueryStatsProcessor,
    storage.thrift StatType:65-69)."""
    from nebula_tpu.storage import StatDef
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    defs = [StatDef("edge", LIKE_EDGE, "likeness", 1),   # SUM
            StatDef("edge", LIKE_EDGE, "", 2),           # COUNT(*)
            StatDef("edge", LIKE_EDGE, "likeness", 3),   # AVG
            StatDef("tag", PLAYER_TAG, "age", 1)]        # SUM of src ages
    vids = [100, 101, 102, 103]
    resp = client.bound_stats(1, vids, [LIKE_EDGE], defs)
    assert all(r.code == ErrorCode.SUCCEEDED for r in resp.results.values())
    total, cnt, avg, ages = resp.finalize(defs)
    # 5 like edges: 95+95+95+90+75 = 450
    assert cnt == 5
    assert total == pytest.approx(450.0)
    assert avg == pytest.approx(90.0)
    assert ages == 42 + 36 + 41 + 33


def test_bound_stats_with_filter(cluster):
    from nebula_tpu.storage import StatDef
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    flt = encode_expression(parse_expr("like.likeness >= 95"))
    defs = [StatDef("edge", LIKE_EDGE, "", 2)]
    resp = client.bound_stats(1, [100, 101, 102, 103], [LIKE_EDGE], defs,
                              filter_bytes=flt)
    assert resp.finalize(defs) == [3]


def test_bound_stats_count_string_prop_and_pad_clamp(cluster):
    """COUNT of a non-numeric prop counts non-null values (review fix)."""
    from nebula_tpu.storage import StatDef
    from nebula_tpu.filter.functions import FunctionManager
    sm, store, svc, client, player, like, serve = cluster
    insert_sample(client, player, like, serve)
    defs = [StatDef("tag", PLAYER_TAG, "name", 2)]  # COUNT of string prop
    resp = client.bound_stats(1, [100, 101, 102, 103], [LIKE_EDGE], defs)
    assert resp.finalize(defs) == [4]
    assert FunctionManager.invoke("lpad", ["abc", -1, "x"]) == ""
    assert FunctionManager.invoke("rpad", ["abc", -5, "x"]) == ""
