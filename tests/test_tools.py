"""Tools tests (parity model: the reference's src/tools — perf driver,
integrity linked-list check, simple KV verify, CSV importer, offline
SST generator)."""
import json
import os

import pytest

from nebula_tpu.cluster import InProcCluster


@pytest.fixture()
def cluster():
    c = InProcCluster()
    conn = c.connect()
    conn.must("CREATE SPACE tool_space(partition_num=4)")
    conn.must("USE tool_space")
    conn.must("CREATE TAG test_tag(test_prop int)")
    conn.must("CREATE EDGE test_edge(weight double)")
    space_id = c.meta.get_space("tool_space").value().space_id
    return c, conn, space_id


def test_storage_perf(cluster):
    from nebula_tpu.tools.storage_perf import run_perf
    c, conn, space_id = cluster
    tag_id = c.sm.tag_id(space_id, "test_tag")
    etype = c.sm.edge_type(space_id, "test_edge")
    out = run_perf(c.client, c.sm, space_id, tag_id, etype,
                   method="addVertices", total_reqs=50, concurrency=4,
                   size=8, min_vid=1, max_vid=100)
    assert out["errors"] == 0 and out["total_reqs"] == 50
    assert out["qps"] > 0 and out["latency_us"]["p99"] >= out["latency_us"]["p50"]
    out = run_perf(c.client, c.sm, space_id, tag_id, etype,
                   method="getNeighbors", total_reqs=50, concurrency=4,
                   size=8, min_vid=1, max_vid=100)
    assert out["errors"] == 0


def test_storage_perf_unknown_method(cluster):
    from nebula_tpu.tools.storage_perf import run_perf
    c, conn, space_id = cluster
    with pytest.raises(ValueError):
        run_perf(c.client, c.sm, space_id, 1, 1, method="nope")


def test_integrity_circle(cluster):
    from nebula_tpu.tools.integrity_check import run_integrity
    c, conn, space_id = cluster
    tag_id = c.sm.tag_id(space_id, "test_tag")
    out = run_integrity(c.client, c.sm, space_id, tag_id, "test_prop",
                        width=5, height=4, first_vid=1000)
    assert out["ok"], out
    assert out["steps"] == 20


def test_integrity_detects_break(cluster):
    from nebula_tpu.tools.integrity_check import prepare_data, validate
    c, conn, space_id = cluster
    tag_id = c.sm.tag_id(space_id, "test_tag")
    prepare_data(c.client, c.sm, space_id, tag_id, "test_prop", 4, 3,
                 first_vid=5000)
    # corrupt one link: vid 5003 now points outside the circle
    conn.must("UPDATE VERTEX 5003 SET test_tag.test_prop = 99999")
    out = validate(c.client, c.sm, space_id, tag_id, "test_prop", 5000, 12)
    assert not out["ok"]


def test_kv_verify(cluster):
    from nebula_tpu.tools.kv_verify import run_kv_verify
    c, conn, space_id = cluster
    out = run_kv_verify(c.client, space_id, count=100, value_size=32)
    assert out["ok"], out
    assert out["mismatches"] == 0


def test_csv_importer(cluster, tmp_path):
    from nebula_tpu.tools.importer import import_csv
    c, conn, space_id = cluster
    conn.must("CREATE TAG player(name string, age int)")
    conn.must("CREATE EDGE like(likeness double)")
    (tmp_path / "players.csv").write_text(
        "id,name,age\n100,Tim,42\n101,\"Tony \"\"P\"\"\",36\n102,Manu,41\n")
    (tmp_path / "likes.csv").write_text(
        "src,dst,likeness,r\n100,101,95.5,0\n100,102,90.0,1\n")
    mapping = {
        "space": "tool_space",
        "vertices": [{"file": "players.csv", "tag": "player",
                      "vid_col": "id", "props": ["name", "age"]}],
        "edges": [{"file": "likes.csv", "edge": "like", "src_col": "src",
                   "dst_col": "dst", "rank_col": "r",
                   "props": ["likeness"]}],
    }
    counts = import_csv(conn.execute, mapping, base_dir=str(tmp_path),
                        batch=2)
    assert counts == {"vertices": 3, "edges": 2}
    r = conn.must("FETCH PROP ON player 101 YIELD player.name, player.age")
    assert r.rows[0][-2:] == ('Tony "P"', 36)
    r = conn.must("GO FROM 100 OVER like YIELD like._dst AS d, like._rank AS r")
    assert sorted(r.rows) == [(101, 0), (102, 1)]


def test_sst_generator_offline_then_ingest(cluster, tmp_path):
    """Offline SSTs -> DOWNLOAD (local dir) -> INGEST -> queryable."""
    from nebula_tpu.tools.sst_generator import generate
    c, conn, space_id = cluster
    conn.must("CREATE TAG player(name string, age int)")
    conn.must("CREATE EDGE like(likeness double)")
    (tmp_path / "players.csv").write_text("id,name,age\n300,Kawhi,27\n301,Paul,34\n")
    (tmp_path / "likes.csv").write_text("src,dst,likeness\n300,301,88.0\n")
    tag_id = c.sm.tag_id(space_id, "player")
    etype = c.sm.edge_type(space_id, "like")
    mapping = {
        "num_parts": 4,
        "vertices": [{"file": "players.csv", "tag_id": tag_id,
                      "vid_col": "id",
                      "props": {"name": "string", "age": "int"}}],
        "edges": [{"file": "likes.csv", "edge_type": etype,
                   "src_col": "src", "dst_col": "dst", "rank_col": None,
                   "props": {"likeness": "double"}}],
    }
    out_dir = tmp_path / "sst_out"
    counts = generate(mapping, str(out_dir), base_dir=str(tmp_path))
    assert sum(counts.values()) == 4  # 2 vertices + out-edge + in-edge
    from nebula_tpu.common.flags import storage_flags
    prev = storage_flags.get("download_dir")
    storage_flags.set("download_dir", str(tmp_path / "staging"))
    try:
        conn.must(f'DOWNLOAD HDFS "{out_dir}"')
        conn.must("INGEST")
        r = conn.must("GO FROM 300 OVER like YIELD like._dst AS d")
        assert r.rows == [(301,)]
        r = conn.must("FETCH PROP ON player 301 YIELD player.name")
        assert r.rows[0][-1] == "Paul"
    finally:
        storage_flags.set("download_dir", prev)


def test_tool_clis_parse(capsys):
    """CLI arg wiring sanity: --help exits 0 for every tool."""
    for mod in ("storage_perf", "integrity_check", "kv_verify",
                "importer", "sst_generator"):
        tool = __import__(f"nebula_tpu.tools.{mod}", fromlist=["main"])
        with pytest.raises(SystemExit) as e:
            tool.main(["--help"])
        assert e.value.code == 0
        capsys.readouterr()


def test_console_completer_keywords_and_schema_names():
    """Tab completion offers nGQL verbs plus live space/tag/edge names
    from the catalog (VERDICT r2 item 10; ref console/CliManager.h)."""
    from nba_fixture import load_nba
    from nebula_tpu.console import ConsoleCompleter

    _, conn = load_nba(space="comp")
    comp = ConsoleCompleter(conn)

    def all_matches(text):
        out, i = [], 0
        while True:
            m = comp.complete(text, i)
            if m is None:
                return out
            out.append(m)
            i += 1

    assert "GO " in all_matches("g") or "GO " in all_matches("G")
    assert any(m.startswith("FIND") for m in all_matches("FI"))
    assert "player" in all_matches("pla")       # tag name from catalog
    assert "like" in all_matches("li")          # edge name
    assert "comp" in all_matches("com")         # space name


def test_soak_short():
    """A short mixed INSERT+GO soak: identity checks pass, the delta
    buffer absorbs every write (no foreground rebuilds beyond
    background repacks), and the summary is well-formed."""
    from nebula_tpu.tools.soak import run_soak
    out = run_soak(seconds=2.0, verify_every=5, v=500, e=2000)
    assert out["ok"], out
    assert out["queries"] > 0 and out["writes"] > 0
    assert out["identity_verifies"] > 0


def test_identity_fuzz_short():
    """Randomized CPU/TPU identity search (both engine modes) — any
    divergence fails with the reproducing query."""
    from nebula_tpu.tools.identity_fuzz import run_fuzz
    out = run_fuzz(rounds=40, seed=101, n_v=60, n_e=300)
    assert out["ok"], out
    dense = run_fuzz(rounds=30, seed=102, n_v=60, n_e=300,
                     sparse_budget=0)
    assert dense["ok"], dense
    # zero-edge frontiers may still serve sparsely (visiting nothing is
    # under any budget) — assert the dense dispatch did real work
    served = dense["served"]
    assert served["go_served"] - served["sparse_served"] > 0, served


def test_session_bench_sweep():
    """Multi-session concurrency bench against a real TCP graphd (the
    StoragePerfTool methodology at the query layer): every sweep point
    completes queries error-free and reports sane latencies."""
    from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
    from nebula_tpu.sample import LIKES, PLAYERS
    from nebula_tpu.client import GraphClient
    from nebula_tpu.tools.session_bench import sweep

    metad = serve_metad()
    sd = serve_storaged(metad.addr, load_interval=0.1)
    graphd = serve_graphd(metad.addr)
    try:
        c = GraphClient(graphd.addr).connect()
        stmts = ["CREATE SPACE nba(partition_num=4)", "USE nba",
                 "CREATE TAG player(name string, age int)",
                 "CREATE EDGE like(likeness double)",
                 "INSERT VERTEX player(name, age) VALUES " + ", ".join(
                     f'{v}:("{n}", {a})' for v, n, a in PLAYERS),
                 "INSERT EDGE like(likeness) VALUES " + ", ".join(
                     f"{s} -> {d}:({w})" for s, d, w in LIKES)]
        for stmt in stmts:
            r = c.execute(stmt)
            assert r.ok(), (stmt, r.error_msg)
        out = sweep(graphd.addr,
                    ["GO FROM 100 OVER like YIELD like._dst",
                     "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
                     "FETCH PROP ON player 101 YIELD player.name"],
                    session_counts=(1, 4), duration_s=0.8,
                    use_space="nba")
        assert len(out) == 2
        for rec in out:
            assert rec["errors"] == 0, rec
            assert rec["total_queries"] > 0
            assert rec["latency_ms"]["p99"] >= rec["latency_ms"]["p50"]
        assert out[1]["n_sessions"] == 4
    finally:
        for h in (graphd, sd, metad):
            h.stop()


def test_sst_generator_parallel_matches_serial(cluster, tmp_path):
    """generate_parallel (the Spark scale-out role: input splits ->
    per-worker sorted runs -> k-way merge) produces byte-identical
    per-part files to the serial path, modulo row-version stamps —
    compared here at the key-set level, and end-to-end via INGEST."""
    import random

    from nebula_tpu.storage.sst import part_file, read_sst
    from nebula_tpu.tools.sst_generator import generate, generate_parallel

    c, conn, space_id = cluster
    conn.must("CREATE TAG pplayer(name string, age int)")
    conn.must("CREATE EDGE plike(likeness double)")
    rng = random.Random(5)
    n_v, n_e = 200, 500
    vlines = ["id,name,age"] + [f"{400 + i},P{i},{20 + i % 30}"
                                for i in range(n_v)]
    elines = ["src,dst,likeness"] + [
        f"{400 + rng.randrange(n_v)},{400 + rng.randrange(n_v)},"
        f"{rng.randrange(100)}.5" for _ in range(n_e)]
    (tmp_path / "pv.csv").write_text("\n".join(vlines) + "\n")
    (tmp_path / "pe.csv").write_text("\n".join(elines) + "\n")
    mapping = {
        "num_parts": 4,
        "vertices": [{"file": "pv.csv",
                      "tag_id": c.sm.tag_id(space_id, "pplayer"),
                      "vid_col": "id",
                      "props": {"name": "string", "age": "int"}}],
        "edges": [{"file": "pe.csv",
                   "edge_type": c.sm.edge_type(space_id, "plike"),
                   "src_col": "src", "dst_col": "dst", "rank_col": None,
                   "props": {"likeness": "double"}}],
    }
    serial = generate(mapping, str(tmp_path / "serial"),
                      base_dir=str(tmp_path))
    par = generate_parallel(mapping, str(tmp_path / "par"),
                            base_dir=str(tmp_path), workers=3)
    assert serial == par                      # same per-part counts
    assert sum(par.values()) == n_v + 2 * n_e
    for p in par:
        ks = [k[:-8] for k, _ in read_sst(str(tmp_path / "serial"
                                              / part_file(p)))]
        kp = [k[:-8] for k, _ in read_sst(str(tmp_path / "par"
                                              / part_file(p)))]
        assert sorted(ks) == sorted(kp)       # version-stripped keys
    from nebula_tpu.common.flags import storage_flags
    prev = storage_flags.get("download_dir")
    storage_flags.set("download_dir", str(tmp_path / "staging2"))
    try:
        conn.must(f'DOWNLOAD HDFS "{tmp_path / "par"}"')
        conn.must("INGEST")
        r = conn.must("FETCH PROP ON pplayer 400 YIELD pplayer.name")
        assert r.rows[0][-1] == "P0"
    finally:
        storage_flags.set("download_dir", prev)


def test_soak_concurrent_short():
    """Multi-session dispatcher soak: concurrent readers/writers over
    one engine (delta applies + aligned invalidation racing batched
    rounds), identity swept after every burst phase."""
    from nebula_tpu.tools.soak import run_soak_concurrent
    out = run_soak_concurrent(seconds=4.0, threads=5, v=800, e=4000)
    assert out["ok"], out
    assert not out["errors"], out
    assert out["dispatcher"]["batched_queries"] > 0, out


def test_watchdog_fake_up_self_test(tmp_path):
    """Satellite (ISSUE 1): the watchdog's probe-SUCCESS branch —
    trimmed-bench capture, then escalation to the full bench — has
    never run on a CPU-only box; --fake-up forces it deterministically
    against a stand-in bench, with artifacts redirected away from the
    real capture files."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "tpu_watchdog.py")
    standin = tmp_path / "standin_metrics.py"
    standin.write_text(
        "import json, os\n"
        "print(json.dumps({'metric': 'selftest', 'value': 1.0,\n"
        "                  'unit': 'edges/s',\n"
        "                  'platform': 'cpu-fallback(fake-up)',\n"
        "                  'trim': os.environ.get('BENCH_V', '')}))\n")
    env = dict(os.environ)
    env.update({
        "WATCHDOG_OUT_TRIM": str(tmp_path / "trim.json"),
        "WATCHDOG_OUT_FULL": str(tmp_path / "full.json"),
        "WATCHDOG_LOG": str(tmp_path / "wd.log"),
        "WATCHDOG_BENCH_SCRIPT": str(standin),
    })

    def once():
        return subprocess.run(
            [sys.executable, "-S", script, "--once", "--fake-up"],
            capture_output=True, text=True, timeout=120, env=env)

    # 1st probe success -> trimmed capture
    p1 = once()
    assert p1.returncode == 0, (p1.stdout, p1.stderr)
    trim = json.loads((tmp_path / "trim.json").read_text())
    assert trim["captured_by"] == "tpu_watchdog"
    assert trim["trim"] != "", "trimmed scale env not applied"
    # 2nd probe success with trim in hand -> FULL-bench escalation
    p2 = once()
    assert p2.returncode == 0, (p2.stdout, p2.stderr)
    full = json.loads((tmp_path / "full.json").read_text())
    assert full["captured_by"] == "tpu_watchdog"
    assert full["trim"] == "", "full run must not inherit trim scale"
    log_text = (tmp_path / "wd.log").read_text()
    assert log_text.count("CAPTURED") == 2, log_text
    # without redirected artifacts the self-test must refuse to run
    # (it would overwrite the REAL accelerator captures otherwise)
    bare_env = {k: v for k, v in env.items()
                if k not in ("WATCHDOG_OUT_TRIM", "WATCHDOG_OUT_FULL")}
    p3 = subprocess.run(
        [sys.executable, "-S", script, "--once", "--fake-up"],
        capture_output=True, text=True, timeout=60, env=bare_env)
    assert p3.returncode == 2, (p3.stdout, p3.stderr)
