"""The flagship round-2 requirement: the TPU engine served in the REAL
3-daemon topology — metad + storaged ×2 + graphd over TCP, with the
graphd-side engine feeding its CSR snapshots from remote storaged parts
via the snapshot-sync RPC (scan_part_cols), and serving GO / FIND
SHORTEST PATH with results identical to the CPU fan-out path.

Ref seam: storage/StorageServer.cpp:32-55 (FLAGS_store_type — the
engine plugin boundary lives at the storage service)."""
import time

import pytest

from nba_fixture import LIKES, PLAYERS, SERVES, TEAMS
from nebula_tpu.client import GraphClient
from nebula_tpu.daemons import serve_graphd, serve_metad, serve_storaged
from nebula_tpu.engine_tpu import TpuGraphEngine


def _load_nba_over_network(client):
    client.execute("CREATE SPACE nba(partition_num=4, replica_factor=1)")
    for q in ["USE nba",
              "CREATE TAG player(name string, age int)",
              "CREATE TAG team(name string)",
              "CREATE EDGE like(likeness double)",
              "CREATE EDGE serve(start_year int, end_year int)"]:
        r = client.execute(q)
        assert r.ok(), (q, r.error_msg)
    rows = ", ".join(f'{vid}:("{n}", {a})' for vid, n, a in PLAYERS)
    assert client.execute(
        f"INSERT VERTEX player(name, age) VALUES {rows}").ok()
    rows = ", ".join(f'{vid}:("{n}")' for vid, n in TEAMS)
    assert client.execute(f"INSERT VERTEX team(name) VALUES {rows}").ok()
    rows = ", ".join(f"{s} -> {d}:({w})" for s, d, w in LIKES)
    assert client.execute(f"INSERT EDGE like(likeness) VALUES {rows}").ok()
    rows = ", ".join(f"{s} -> {d}:({a}, {b})" for s, d, a, b in SERVES)
    assert client.execute(
        f"INSERT EDGE serve(start_year, end_year) VALUES {rows}").ok()


@pytest.fixture(scope="module")
def net_cluster():
    metad = serve_metad()
    s1 = serve_storaged(metad.addr, load_interval=0.1)
    s2 = serve_storaged(metad.addr, load_interval=0.1)
    tpu = TpuGraphEngine()
    graphd_tpu = serve_graphd(metad.addr, tpu_engine=tpu)
    graphd_cpu = serve_graphd(metad.addr)
    tc = GraphClient(graphd_tpu.addr).connect()
    cc = GraphClient(graphd_cpu.addr).connect()
    _load_nba_over_network(tc)
    assert cc.execute("USE nba").ok()
    yield tc, cc, tpu, (metad, s1, s2)
    tc.disconnect()
    cc.disconnect()
    for h in (graphd_tpu, graphd_cpu, s1, s2, metad):
        h.stop()


QUERIES = [
    "GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w",
    "GO 2 STEPS FROM 100 OVER like YIELD DISTINCT like._dst",
    "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
    "GO FROM 100 OVER like REVERSELY YIELD like._dst",
    "GO FROM 100 OVER like WHERE like.likeness > 80 YIELD like._dst, "
    "like.likeness",
    'GO FROM 100 OVER like WHERE $^.player.age > 40 YIELD like._dst, '
    '$^.player.name',
    'GO FROM 100 OVER serve YIELD $$.team.name AS team',
    "FIND SHORTEST PATH FROM 103 TO 100 OVER like UPTO 8 STEPS",
    "FIND SHORTEST PATH FROM 100, 101 TO 105, 106 OVER like UPTO 6 STEPS",
]


def test_tpu_served_over_real_topology(net_cluster):
    tc, cc, tpu, _ = net_cluster
    before_go = tpu.stats["go_served"]
    before_path = tpu.stats["path_served"]
    for q in QUERIES:
        rt = tc.execute(q)
        rc = cc.execute(q)
        assert rt.ok(), (q, rt.error_msg)
        assert rc.ok(), (q, rc.error_msg)
        assert rt.columns == rc.columns, q
        assert sorted(map(str, rt.rows)) == sorted(map(str, rc.rows)), q
    # the device engine actually served (not a silent CPU fallback)
    assert tpu.stats["go_served"] - before_go >= 7, tpu.stats
    assert tpu.stats["path_served"] - before_path >= 2, tpu.stats


def test_tpu_sees_remote_writes(net_cluster):
    """Freshness across the RPC boundary: a write through graphd must be
    visible to the very next device read — WITHOUT a full snapshot
    rebuild (the committed-write feed patches the CSR in place; ref
    role: Part::commitLogs in-place apply, kvstore/Part.cpp:208-319)."""
    tc, cc, tpu, _ = net_cluster
    assert tc.execute("GO FROM 110 OVER like YIELD like._dst").ok()
    rebuilds0 = tpu.stats["rebuilds"]
    applies0 = tpu.stats["delta_applies"]
    cluster0 = tpu.stats["cluster_served"]
    served0 = tpu.stats["go_served"]
    assert tc.execute(
        "INSERT EDGE like(likeness) VALUES 110 -> 100:(55.0)").ok()
    rt = tc.execute("GO FROM 110 OVER like YIELD like._dst, like.likeness")
    rc = cc.execute("GO FROM 110 OVER like YIELD like._dst, like.likeness")
    assert sorted(map(str, rt.rows)) == sorted(map(str, rc.rows))
    assert (106, 70.0) in rt.rows and (100, 55.0) in rt.rows
    assert tpu.stats["go_served"] > served0, "post-write read left device"
    assert tpu.stats["rebuilds"] == rebuilds0, "write forced a rebuild"
    if tpu.stats["cluster_served"] == cluster0:
        # local-snapshot mode: the committed-write feed must have
        # patched the CSR in place. Under cluster scatter/gather v2
        # there is no graphd-local snapshot to patch — freshness rides
        # the per-part storaged serve, proven by the row asserts above.
        assert tpu.stats["delta_applies"] > applies0
    # and a delete is equally visible, also without a rebuild
    assert tc.execute("DELETE EDGE like 110 -> 100").ok()
    rt = tc.execute("GO FROM 110 OVER like YIELD like._dst")
    assert rt.rows == [(106,)], rt.rows
    assert tpu.stats["rebuilds"] == rebuilds0, "delete forced a rebuild"


def test_no_per_query_version_rpcs(net_cluster):
    """Steady state: the freshness token comes from the push-fed watch
    cache — ZERO per-query version RPCs (the round-2 hot path probed
    every host serving the space on every query; ref role:
    MetaClient.cpp:120-193 caches topology instead of probing)."""
    tc, cc, tpu, _ = net_cluster
    sc = tpu._provider._client
    # one warm-up query may cold-prime the cache with sync probes
    assert tc.execute("GO FROM 100 OVER like YIELD like._dst").ok()
    probes0 = sc.version_stats["probe_rpcs"]
    served0 = tpu.stats["go_served"]
    for _ in range(5):
        r = tc.execute("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
        assert r.ok(), r.error_msg
    assert tpu.stats["go_served"] - served0 == 5, tpu.stats
    assert sc.version_stats["probe_rpcs"] == probes0, sc.version_stats
    assert sc.version_stats["watch_rounds"] > 0


def test_storaged_death_falls_back_to_cpu(net_cluster):
    """Killing a storaged mid-flight: space_versions goes None and the
    engine declines; the query surface stays correct via CPU fan-out
    (single-replica space: parts on the dead host are lost, but the
    graphd must not crash or serve a stale device snapshot)."""
    tc, cc, tpu, (metad, s1, s2) = net_cluster
    # all parts healthy: the engine serves from device
    assert tc.execute("GO FROM 100 OVER like YIELD like._dst").ok()
    # kill BOTH storagds: partition_num=4 hashes parts across the two
    # hosts, so killing one may leave every part this query touches on
    # the survivor — and a fresh-token device serve would then be the
    # CORRECT outcome, not the failure this test is about
    s2.stop()
    s1.stop()
    try:
        fallbacks0 = tpu.stats["fallbacks"]
        # the version watch marks the space stale FAIL-FAST but
        # asynchronously (its long-poll must first hit the dead
        # socket) — poll within a bounded window instead of racing it
        # with a single query
        deadline = time.time() + 5.0
        poll = 0
        while time.time() < deadline and \
                tpu.stats["fallbacks"] == fallbacks0:
            # unique alias per poll: an earlier test warmed this exact
            # query into the result cache, and a still-valid cache hit
            # would answer without ever exercising the serve decision
            # this test is about
            tc.execute(f"GO FROM 100 OVER like YIELD like._dst AS d{poll}")
            poll += 1
            time.sleep(0.05)
        # dead single-replica parts surface as a storage error on the
        # CPU path — either outcome is acceptable, but it must NOT be
        # served from the (now unverifiable) device snapshot
        assert tpu.stats["fallbacks"] > fallbacks0
    finally:
        pass  # fixture teardown stops the rest (s2.stop is idempotent)


def test_tcp_topology_identity_fuzz():
    """Randomized identity over the REAL TCP topology: a --tpu graphd
    and a CPU graphd share one storaged; every random query must return
    identical rows from both, with mutations (including mid-stream
    ALTERs) applied once through the shared store."""
    import random
    import time as _t

    from nebula_tpu.tools.identity_fuzz import (_build_graph,
                                                _rand_mutation,
                                                _rand_query)

    metad = serve_metad()
    s1 = serve_storaged(metad.addr, load_interval=0.1)
    g_cpu = serve_graphd(metad.addr)
    g_tpu = serve_graphd(metad.addr, tpu_engine=TpuGraphEngine())
    try:
        cc = GraphClient(g_cpu.addr).connect()
        ct = GraphClient(g_tpu.addr).connect()
        rnd = random.Random(9001)
        for s in _build_graph(rnd, 80, 400):
            r = cc.execute(s)
            assert r.ok(), (s, r.error_msg)
            if s.startswith("CREATE"):
                _t.sleep(0.05)
        assert ct.execute("USE fz").ok()
        _t.sleep(0.5)
        alters, fresh = [], []
        checked = 0
        for i in range(60):
            if i and i % 6 == 0:
                m = _rand_mutation(rnd, 80, fresh, alters)
                cc.execute(m)
                if m.startswith("ALTER"):
                    _t.sleep(0.4)   # schema watch propagation
                continue
            q = _rand_query(rnd, 80, alters)
            rc, rt = cc.execute(q), ct.execute(q)
            assert rc.code == rt.code, (q, rc.code, rt.code)
            if rc.ok():
                assert sorted(map(repr, rc.rows)) == \
                    sorted(map(repr, rt.rows)), q
            checked += 1
        assert checked > 40
    finally:
        for h in (g_tpu, g_cpu, s1, metad):
            h.stop()
