"""Delta-buffer tests: committed writes patch the device snapshot in
place (no per-write rebuild), with CPU/TPU result identity maintained
through inserts, deletes, prop updates, upserts and path queries.

Ref role: the reference applies every committed write in place
(Part::commitLogs, kvstore/Part.cpp:208-319) so readers never see a
rebuild pause; SURVEY.md §7 names device-side mutability hard-part (a)
and §2.10 P6 the delta-buffer strategy.
"""
import time

import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture()
def pair():
    """Function-scoped: mutation tests need pristine state."""
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    return cpu_conn, tpu_conn, tpu


@pytest.fixture()
def pair_with_cluster():
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    return cpu_conn, tpu_conn, tpu, cluster


def _both(cpu_conn, tpu_conn, stmt):
    rc = cpu_conn.must(stmt)
    rt = tpu_conn.must(stmt)
    return rc, rt


def _identical(cpu_conn, tpu_conn, query):
    rc, rt = _both(cpu_conn, tpu_conn, query)
    assert rc.columns == rt.columns, query
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
        (query, rc.rows, rt.rows)
    return rt


MUTATION_SCRIPTS = [
    # new edge between existing vertices
    ["INSERT EDGE like(likeness) VALUES 110 -> 100:(55.0)"],
    # new vertex + edge to and from it
    ['INSERT VERTEX player(name, age) VALUES 777:("Delta", 33)',
     "INSERT EDGE like(likeness) VALUES 100 -> 777:(91.0)",
     "INSERT EDGE like(likeness) VALUES 777 -> 101:(81.0)"],
    # delete an existing (build-time) edge: canonical tombstone
    ["DELETE EDGE like 100 -> 101"],
    # prop update of an existing edge through UPDATE (atomic op)
    ["UPDATE EDGE 100 -> 101 OF like SET likeness = 96.0"],
    # upsert-insert then delete the same edge (delta add + delta remove)
    ["INSERT EDGE like(likeness) VALUES 104 -> 100:(44.0)",
     "DELETE EDGE like 104 -> 100"],
    # vertex prop update feeding a $^ filter
    ["UPDATE VERTEX 100 SET player.age = $^.player.age + 10"],
]

CHECK_QUERIES = [
    "GO FROM 100 OVER like YIELD like._dst, like.likeness",
    "GO FROM 110 OVER like YIELD like._dst, like.likeness",
    "GO 2 STEPS FROM 100 OVER like YIELD DISTINCT like._dst",
    "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
    "GO FROM 100 OVER like REVERSELY YIELD like._dst",
    "GO FROM 100 OVER like WHERE like.likeness > 80 YIELD like._dst, "
    "like.likeness",
    'GO FROM 100 OVER like WHERE $^.player.age > 40 YIELD like._dst, '
    '$^.player.name',
    "GO FROM 777 OVER like YIELD like._dst",
    "GO FROM 100, 777 OVER like YIELD like._dst",
    "FIND SHORTEST PATH FROM 103 TO 100 OVER like UPTO 8 STEPS",
    "FIND SHORTEST PATH FROM 100 TO 777 OVER like UPTO 4 STEPS",
    "FIND ALL PATH FROM 100 TO 777 OVER like UPTO 3 STEPS",
    "FIND NOLOOP PATH FROM 103 TO 777 OVER like UPTO 5 STEPS",
]


@pytest.mark.parametrize("script", MUTATION_SCRIPTS,
                         ids=[s[0][:40] for s in MUTATION_SCRIPTS])
def test_mutations_patch_without_rebuild(pair, script):
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")          # snapshot exists
    rebuilds0 = tpu.stats["rebuilds"]
    for stmt in script:
        _both(cpu_conn, tpu_conn, stmt)
    for q in CHECK_QUERIES:
        _identical(cpu_conn, tpu_conn, q)
    assert tpu.stats["rebuilds"] == rebuilds0, \
        f"writes forced {tpu.stats['rebuilds'] - rebuilds0} rebuild(s)"
    assert tpu.stats["go_served"] > 0


def test_mixed_write_read_stream(pair):
    """Interleaved INSERT+GO: the device path serves continuously (no
    rebuild per write) — the VERDICT r2 done-criterion."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")
    rebuilds0 = tpu.stats["rebuilds"]
    served0 = tpu.stats["go_served"]
    for i in range(20):
        vid = 9000 + i
        _both(cpu_conn, tpu_conn,
              f'INSERT VERTEX player(name, age) VALUES {vid}:("w{i}", {20+i})')
        _both(cpu_conn, tpu_conn,
              f"INSERT EDGE like(likeness) VALUES 100 -> {vid}:({50+i}.0)")
        _identical(cpu_conn, tpu_conn,
                   "GO FROM 100 OVER like YIELD like._dst, like.likeness")
    assert tpu.stats["rebuilds"] == rebuilds0
    assert tpu.stats["go_served"] - served0 == 20
    assert tpu.stats["delta_edges"] >= 20


def test_tombstone_then_reinsert(pair):
    """Deleting a build-time edge then re-inserting it must restore the
    canonical slot (untombstone), with fresh props."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")
    rebuilds0 = tpu.stats["rebuilds"]
    _both(cpu_conn, tpu_conn, "DELETE EDGE like 100 -> 101")
    _identical(cpu_conn, tpu_conn,
               "GO FROM 100 OVER like YIELD like._dst, like.likeness")
    _both(cpu_conn, tpu_conn,
          "INSERT EDGE like(likeness) VALUES 100 -> 101:(12.5)")
    r = _identical(cpu_conn, tpu_conn,
                   "GO FROM 100 OVER like YIELD like._dst, like.likeness")
    assert (101, 12.5) in r.rows
    assert tpu.stats["rebuilds"] == rebuilds0
    snap = list(tpu._snapshots.values())[0]
    assert snap.delta is None or snap.delta.edge_count == 0, \
        "re-insert should reuse the canonical slot, not a delta lane"


def test_delta_overflow_triggers_repack(pair):
    """When the delta fills, the engine repacks (off the query path) and
    keeps answering correctly throughout."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")
    snap = list(tpu._snapshots.values())[0]
    from nebula_tpu.engine_tpu.delta import SnapshotDelta
    snap.delta = SnapshotDelta(snap, max_edges=6)   # tiny: force overflow
    for i in range(8):
        vid = 9100 + i
        _both(cpu_conn, tpu_conn,
              f'INSERT VERTEX player(name, age) VALUES {vid}:("o{i}", 30)')
        _both(cpu_conn, tpu_conn,
              f"INSERT EDGE like(likeness) VALUES 101 -> {vid}:(60.0)")
        _identical(cpu_conn, tpu_conn,
                   "GO FROM 101 OVER like YIELD like._dst")
    deadline = time.time() + 10
    while tpu._repacking.get(snap.space_id) and time.time() < deadline:
        time.sleep(0.05)
    assert tpu.stats["rebuilds"] >= 1
    _identical(cpu_conn, tpu_conn, "GO FROM 101 OVER like YIELD like._dst")


def test_compaction_does_not_rebuild(pair_with_cluster):
    """admin compaction removes superseded versions/tombstone keys; the
    resolved delta feed sees no visible change — no rebuild, same
    results."""
    cpu_conn, tpu_conn, tpu, cluster = pair_with_cluster
    # create some superseded versions of an existing edge
    _both(cpu_conn, tpu_conn,
          "INSERT EDGE like(likeness) VALUES 100 -> 101:(91.0)")
    _both(cpu_conn, tpu_conn,
          "INSERT EDGE like(likeness) VALUES 100 -> 101:(92.0)")
    tpu_conn.must("GO FROM 100 OVER like")
    rebuilds0 = tpu.stats["rebuilds"]
    space_id = list(tpu._snapshots.keys())[0]
    st, removed = cluster.storage.admin_compact(space_id)
    assert st.ok()
    assert removed > 0   # the superseded versions really were dropped
    r = _identical(cpu_conn, tpu_conn,
                   "GO FROM 100 OVER like YIELD like._dst, like.likeness")
    assert (101, 92.0) in r.rows
    assert tpu.stats["rebuilds"] == rebuilds0


def test_repack_failure_surfaced_and_backed_off(pair, monkeypatch, caplog):
    """A failing background repack must never be silent (round-3
    verdict weak #3; ref role: every background path logs,
    kvstore/raftex/RaftPart.cpp): logged with traceback, counted in
    engine stats + the global /get_stats metric, retried only after
    backoff — and the previous snapshot keeps serving correctly."""
    import logging

    from nebula_tpu.common.stats import stats as gstats

    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")
    sid = list(tpu._snapshots.values())[0].space_id
    g0 = gstats.read_stats("tpu_engine.repack_failures.sum.60") or 0

    def _wait_done():
        deadline = time.time() + 10
        while tpu._repacking.get(sid) and time.time() < deadline:
            time.sleep(0.02)

    orig = tpu._build_fresh

    def boom(_sid):
        raise RuntimeError("synthetic build failure")

    monkeypatch.setattr(tpu, "_build_fresh", boom)
    with caplog.at_level(logging.ERROR, logger="nebula_tpu.engine_tpu"):
        tpu._kick_repack(sid)
        _wait_done()
    assert tpu.stats["repack_failures"] == 1
    assert "background repack" in caplog.text
    assert "synthetic build failure" in caplog.text
    assert (gstats.read_stats("tpu_engine.repack_failures.sum.60")
            or 0) >= g0 + 1
    # an immediate re-kick sits out the backoff window: no new attempt
    tpu._kick_repack(sid)
    _wait_done()
    assert tpu.stats["repack_failures"] == 1
    # with the window forced open the retry runs (and fails again)
    n, _ = tpu._repack_backoff[sid]
    tpu._repack_backoff[sid] = (n, 0.0)
    tpu._kick_repack(sid)
    _wait_done()
    assert tpu.stats["repack_failures"] == 2
    # the poisoned repack never touched serving: previous snapshot
    # still answers, identical to CPU
    _identical(cpu_conn, tpu_conn, "GO FROM 100 OVER like YIELD like._dst")
    # recovery resets the backoff state
    monkeypatch.setattr(tpu, "_build_fresh", orig)
    tpu._repack_backoff[sid] = (tpu._repack_backoff[sid][0], 0.0)
    tpu._kick_repack(sid)
    _wait_done()
    assert sid not in tpu._repack_backoff
    assert tpu.stats["bg_repacks"] >= 1


def test_tag_tombstone_reads_default_on_vectorized_paths(pair):
    """Deleting a vertex resets its mirror cells: WHERE over the
    snapshot's host/device tag columns must read the schema default
    (0), not the stale pre-delete value (round-4 review finding)."""
    cpu_conn, tpu_conn, tpu = pair
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES 9300:("T", 70)')
        conn.must("INSERT EDGE like(likeness) VALUES 100 -> 9300:(50.0)")
    q = "GO FROM 100 OVER like WHERE $$.player.age > 60 YIELD like._dst"
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(rc.rows) == sorted(rt.rows)
    assert (9300,) in rc.rows
    # delete the DST vertex only — its edge remains; age now reads 0
    for conn in (cpu_conn, tpu_conn):
        conn.must("DELETE VERTEX 9300")
    # re-link 100 -> 9300 (DELETE VERTEX removed its edges)
    for conn in (cpu_conn, tpu_conn):
        conn.must("INSERT EDGE like(likeness) VALUES 100 -> 9300:(50.0)")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert (9300,) not in rc.rows           # default 0 is not > 60
    q2 = "GO FROM 100 OVER like WHERE $$.player.age <= 60 YIELD like._dst"
    rc2, rt2 = cpu_conn.must(q2), tpu_conn.must(q2)
    assert sorted(map(repr, rc2.rows)) == sorted(map(repr, rt2.rows))
    assert (9300,) in rc2.rows              # default 0 <= 60: kept
    # YIELD of the defaulted prop agrees too
    q3 = "GO FROM 100 OVER like YIELD like._dst, $$.player.name"
    rc3, rt3 = cpu_conn.must(q3), tpu_conn.must(q3)
    assert sorted(map(repr, rc3.rows)) == sorted(map(repr, rt3.rows))
    assert (9300, "") in rc3.rows


def test_delta_old_version_row_declines_vectorized_tags(pair):
    """An ALTERed tag + a delta write encoded at the OLD version: the
    new prop is a CPU EvalError for that row — the vectorized paths
    must not silently default it (round-4 review finding)."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")       # snapshot up
    for conn in (cpu_conn, tpu_conn):
        conn.must("ALTER TAG player ADD (mvp int)")
        # new writes encode at the NEW version; old build-time rows
        # keep their version -> their mvp cells are version-missing
        conn.must('INSERT VERTEX player(name, age, mvp) '
                  'VALUES 9301:("M", 30, 5)')
        conn.must("INSERT EDGE like(likeness) VALUES 100 -> 9301:(60.0)")
    # dsts include OLD-version vertices (mvp -> EvalError drops them
    # in WHERE) and the new one (mvp = 5)
    q = "GO FROM 100 OVER like WHERE $$.player.mvp >= 0 YIELD like._dst"
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert (9301,) in rc.rows
