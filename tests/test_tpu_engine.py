"""TPU engine tests: CSR snapshot correctness + CPU/TPU result-set equality
(the north-star requirement: identical result sets, BASELINE.json).

Runs on the CPU XLA backend (conftest forces JAX_PLATFORMS=cpu with 8
virtual devices); the same code paths run unchanged on a real chip.
"""
import numpy as np
import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture(scope="module")
def pair():
    """(cpu_conn, tpu_conn, tpu_engine): same NBA data, two engines."""
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    return cpu_conn, tpu_conn, tpu


EQUALITY_QUERIES = [
    "GO FROM 100 OVER like",
    "GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w",
    "GO FROM 100 OVER like REVERSELY YIELD like._dst AS id",
    "GO FROM 102 OVER like BIDIRECT YIELD like._dst AS id",
    "GO 2 STEPS FROM 100 OVER like YIELD DISTINCT like._dst",
    "GO 3 STEPS FROM 100 OVER like YIELD like._dst",
    "GO UPTO 3 STEPS FROM 103 OVER like YIELD like._dst AS id",
    "GO FROM 100, 101, 107 OVER like YIELD like._dst, like.likeness",
    "GO FROM 101 OVER * YIELD _dst AS d",
    "GO FROM 100 OVER like, serve YIELD _dst AS d",
    "GO FROM 100 OVER like WHERE like.likeness > 92 YIELD like._dst",
    "GO FROM 100 OVER like WHERE like.likeness > 80 && like.likeness < 93 "
    "YIELD like._dst, like.likeness",
    'GO FROM 100 OVER like WHERE $^.player.age > 40 YIELD like._dst, $^.player.name',
    'GO FROM 100 OVER serve YIELD $$.team.name AS team',
    'GO FROM 100 OVER like WHERE $$.player.age > 33 YIELD like._dst, $$.player.age',
    'GO FROM 100 OVER serve WHERE $$.team.name == "Spurs" YIELD serve.start_year',
    "GO FROM 100 OVER like YIELD like._src AS s, like._dst AS d, like._rank AS r",
    "GO 2 STEPS FROM 100 OVER like WHERE like.likeness >= 90 YIELD like._dst, like.likeness",
    "GO FROM 121 OVER like",  # empty frontier
    "FIND SHORTEST PATH FROM 100 TO 102 OVER like UPTO 4 STEPS",
    "FIND SHORTEST PATH FROM 103 TO 106 OVER like UPTO 5 STEPS",
    "FIND SHORTEST PATH FROM 103 TO 100 OVER like UPTO 8 STEPS",
    "FIND SHORTEST PATH FROM 100 TO 121 OVER like UPTO 4 STEPS",  # no path
    "FIND SHORTEST PATH FROM 100, 101 TO 105, 106 OVER like UPTO 6 STEPS",
    "FIND SHORTEST PATH FROM 102 TO 104 OVER like, serve UPTO 6 STEPS",
]


@pytest.mark.parametrize("query", EQUALITY_QUERIES)
def test_cpu_tpu_identical_results(pair, query):
    cpu_conn, tpu_conn, tpu = pair
    r_cpu = cpu_conn.must(query)
    r_tpu = tpu_conn.must(query)
    assert r_cpu.columns == r_tpu.columns
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        f"result divergence for: {query}"


def test_device_actually_served(pair):
    cpu_conn, tpu_conn, tpu = pair
    before = tpu.stats["go_served"]
    tpu_conn.must("GO FROM 100 OVER like")
    assert tpu.stats["go_served"] == before + 1
    before_p = tpu.stats["path_served"]
    tpu_conn.must("FIND SHORTEST PATH FROM 100 TO 102 OVER like UPTO 4 STEPS")
    assert tpu.stats["path_served"] == before_p + 1


def test_snapshot_patches_after_mutation(pair):
    """Writes no longer force a rebuild: the committed-write feed
    patches the device snapshot in place (delta buffer, SURVEY §7
    hard-part (a)); results reflect the write immediately."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")   # snapshot exists
    rebuilds = tpu.stats["rebuilds"]
    applies = tpu.stats["delta_applies"]
    tpu_conn.must('INSERT VERTEX player(name, age) VALUES 500:("Newbie", 20)')
    tpu_conn.must('INSERT EDGE like(likeness) VALUES 100 -> 500:(88.0)')
    r = tpu_conn.must("GO FROM 100 OVER like YIELD like._dst AS id")
    assert (500,) in r.rows
    assert tpu.stats["rebuilds"] == rebuilds, "write forced a full rebuild"
    assert tpu.stats["delta_applies"] > applies
    # and unchanged data stays cached
    rebuilds = tpu.stats["rebuilds"]
    tpu_conn.must("GO FROM 100 OVER like")
    assert tpu.stats["rebuilds"] == rebuilds
    # deletes are patched too (tombstone + delta removal)
    tpu_conn.must("DELETE VERTEX 500")
    r = tpu_conn.must("GO FROM 100 OVER like YIELD like._dst AS id")
    assert (500,) not in r.rows
    assert tpu.stats["rebuilds"] == rebuilds, "delete forced a full rebuild"
    cpu_conn.must("GO FROM 100 OVER like")  # keep cpu side warm/symmetric


def test_input_ref_pipe_identity(pair):
    cpu_conn, tpu_conn, tpu = pair
    q = ("GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w | "
         "GO FROM $-.id OVER like YIELD $-.w AS base, like.likeness AS w2")
    r_cpu = cpu_conn.must(q)
    r_tpu = tpu_conn.must(q)
    assert sorted(r_cpu.rows) == sorted(r_tpu.rows)


def test_string_filter_on_device(pair):
    cpu_conn, tpu_conn, tpu = pair
    q = ('GO FROM 100, 101, 102 OVER serve WHERE $$.team.name == "Spurs" '
         'YIELD serve._dst, serve.start_year')
    r_cpu = cpu_conn.must(q)
    before = tpu.stats["go_served"]
    r_tpu = tpu_conn.must(q)
    assert tpu.stats["go_served"] == before + 1
    assert sorted(r_cpu.rows) == sorted(r_tpu.rows)


def test_csr_snapshot_shapes():
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="mini", parts=3)
    space_id = cluster.meta.get_space("mini").value().space_id
    snap = tpu.snapshot(space_id)
    assert snap.num_parts == 3
    assert snap.cap_v % 128 == 0 and snap.cap_e % 128 == 0
    # every inserted edge appears twice (out + reverse copy)
    from nba_fixture import LIKES, SERVES
    assert snap.total_edges == 2 * (len(LIKES) + len(SERVES))
    # locate round-trips
    for vid in (100, 204, 121):
        p, local = snap.locate(vid)
        assert int(snap.shards[p].vids[local]) == vid
    assert snap.locate(99999) is None


@pytest.fixture()
def two_edge_types():
    """Graph with two edge types sharing prop names — the review-found
    divergence repros (qualified filters, string dict collisions)."""
    tpu = TpuGraphEngine()
    cpu_cluster = InProcCluster()
    tpu_cluster = InProcCluster(tpu_engine=tpu)
    conns = []
    for cluster in (cpu_cluster, tpu_cluster):
        c = cluster.connect()
        c.must("CREATE SPACE tw(partition_num=2, replica_factor=1)")
        c.must("USE tw")
        c.must("CREATE TAG node(name string)")
        c.must("CREATE EDGE e1(w int, city string)")
        c.must("CREATE EDGE e2(w int, city string)")
        c.must('INSERT VERTEX node(name) VALUES 1:("a"), 2:("b"), 3:("c")')
        c.must('INSERT EDGE e1(w, city) VALUES 1 -> 2:(10, "NY")')
        c.must('INSERT EDGE e2(w, city) VALUES 1 -> 3:(10, "LA")')
        conns.append(c)
    return conns[0], conns[1], tpu


@pytest.mark.parametrize("query", [
    "GO FROM 1 OVER e1, e2 WHERE e1.w > 5 YIELD _dst AS d",
    'GO FROM 1 OVER e1, e2 WHERE e1.city == "NY" YIELD _dst AS d',
    'GO FROM 1 OVER e1, e2 WHERE city == "LA" YIELD _dst AS d',
    'GO FROM 1 OVER e1, e2 WHERE city != "NY" YIELD _dst AS d',
    "GO FROM 1 OVER e1, e2 WHERE w > 5 YIELD _dst AS d",
])
def test_qualified_and_string_filters_identical(two_edge_types, query):
    cpu, tpu_conn, tpu = two_edge_types
    r_cpu = cpu.must(query)
    before = tpu.stats["go_served"]
    r_tpu = tpu_conn.must(query)
    assert sorted(r_cpu.rows) == sorted(r_tpu.rows), query
    assert tpu.stats["go_served"] == before + 1  # served on device


def test_sparse_partition_keeps_device_filter(two_edge_types):
    """A partition with zero rows of an etype must not kill the device
    filter path (zero-filled absent columns instead of None)."""
    cpu, tpu_conn, tpu = two_edge_types
    snap = tpu.snapshot(tpu_conn._service.engine.meta.get_space("tw").value().space_id)
    assert snap.device_edge_prop(1, "w") is not None


def test_upto_cycle_multiplicity_identical(two_edge_types):
    """Cycle 1->2->1: UPTO re-traverses edges at later steps; row
    multiplicity must match the CPU path (device declines UPTO)."""
    cpu, tpu_conn, tpu = two_edge_types
    for c in (cpu, tpu_conn):
        c.must('INSERT EDGE e1(w, city) VALUES 2 -> 1:(1, "X")')
    q = "GO UPTO 3 STEPS FROM 1 OVER e1 YIELD e1._dst AS d"
    r_cpu = cpu.must(q)
    r_tpu = tpu_conn.must(q)
    assert sorted(r_cpu.rows) == sorted(r_tpu.rows)
    assert sorted(r_cpu.rows).count((2,)) == 2  # edge 1->2 at steps 1 and 3


def test_batched_count_identity(pair):
    """multi_hop_count_batch (aligned frontier-matrix path) must count
    exactly what per-query multi_hop_count counts."""
    import jax.numpy as jnp
    import numpy as np
    from nebula_tpu.engine_tpu import traverse
    _, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")   # force the snapshot
    snap = list(tpu._snapshots.values())[0]
    seeds = [[100], [101, 102], [103, 104, 105], [100, 110]]
    f_batch = jnp.asarray(np.stack(
        [snap.frontier_from_vids(s) for s in seeds]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    for steps in (1, 2, 3):
        ak, chunk, group = snap.aligned_kernel()
        batch = np.asarray(traverse.multi_hop_count_batch(
            f_batch, jnp.int32(steps), ak, req, chunk=chunk, group=group))
        for i, s in enumerate(seeds):
            single = int(traverse.multi_hop_count(
                jnp.asarray(snap.frontier_from_vids(s)), jnp.int32(steps),
                snap.kernel, req))
            assert int(batch[i]) == single, (steps, s, batch[i], single)


UPTO_INPUT_QUERIES = [
    "GO UPTO 3 STEPS FROM 103 OVER like YIELD like._dst AS id",
    "GO UPTO 2 STEPS FROM 100 OVER like YIELD like._dst, like.likeness",
    "GO UPTO 4 STEPS FROM 100 OVER like WHERE like.likeness > 80 "
    "YIELD like._dst, like.likeness",
    "GO UPTO 2 STEPS FROM 100, 101 OVER like YIELD DISTINCT like._dst",
    # $- input back-references through a pipe (per-root device frontiers)
    "GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w | "
    "GO FROM $-.id OVER like YIELD $-.w AS base, like.likeness AS w2",
    "GO FROM 100 OVER like YIELD like._dst AS id | "
    "GO 2 STEPS FROM $-.id OVER like YIELD $-.id AS root, like._dst",
    "GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w | "
    "GO FROM $-.id OVER like WHERE $-.w > 80 YIELD $-.w, like._dst",
    # $var back-references
    "$a = GO FROM 100 OVER like YIELD like._dst AS id, like.likeness AS w; "
    "GO FROM $a.id OVER like YIELD $a.w AS base, like._dst",
]


@pytest.mark.parametrize("query", UPTO_INPUT_QUERIES)
def test_upto_and_input_ref_served_on_device(pair, query):
    """GO UPTO (per-step masks) and $-/$var input-ref GO (per-root
    frontiers) now run on device with identical results (VERDICT r2
    item 6; ref GoExecutor upto emission + VertexBackTracker)."""
    cpu_conn, tpu_conn, tpu = pair
    r_cpu = cpu_conn.must(query)
    before = tpu.stats["go_served"]
    r_tpu = tpu_conn.must(query)
    assert r_cpu.columns == r_tpu.columns, query
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        (query, r_cpu.rows, r_tpu.rows)
    assert tpu.stats["go_served"] > before, f"not device-served: {query}"


ALL_PATH_QUERIES = [
    "FIND ALL PATH FROM 100 TO 102 OVER like UPTO 4 STEPS",
    "FIND ALL PATH FROM 103 TO 100 OVER like UPTO 5 STEPS",
    "FIND ALL PATH FROM 100, 101 TO 105 OVER like UPTO 4 STEPS",
    "FIND ALL PATH FROM 100 TO 121 OVER like UPTO 4 STEPS",   # no path
    "FIND NOLOOP PATH FROM 100 TO 102 OVER like UPTO 4 STEPS",
    "FIND NOLOOP PATH FROM 103 TO 106 OVER like UPTO 6 STEPS",
    "FIND ALL PATH FROM 102 TO 104 OVER like, serve UPTO 4 STEPS",
]


@pytest.mark.parametrize("query", ALL_PATH_QUERIES)
def test_all_path_served_on_device(pair, query):
    """FIND ALL/NOLOOP PATH now runs its per-hop expansion on device
    (per-level masks); enumeration shares the CPU loop so results are
    identical by construction (VERDICT r2 item 8)."""
    cpu_conn, tpu_conn, tpu = pair
    r_cpu = cpu_conn.must(query)
    before = tpu.stats["path_served"]
    r_tpu = tpu_conn.must(query)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        (query, r_cpu.rows, r_tpu.rows)
    assert tpu.stats["path_served"] > before, f"not device-served: {query}"


def test_all_path_random_graph_identity():
    """ALL/NOLOOP/SHORTEST path identity on a denser random graph (the
    NBA fixture's path space is narrow; this exercises multiplicity)."""
    import numpy as np
    rng = np.random.default_rng(11)
    tpu = TpuGraphEngine()
    cpu_cluster = InProcCluster()
    tpu_cluster = InProcCluster(tpu_engine=tpu)
    conns = []
    V, E = 60, 300
    edges = {(int(s), int(d)) for s, d in
             zip(rng.integers(0, V, E), rng.integers(0, V, E)) if s != d}
    for cluster in (cpu_cluster, tpu_cluster):
        c = cluster.connect()
        c.must("CREATE SPACE rnd(partition_num=3, replica_factor=1)")
        c.must("USE rnd")
        c.must("CREATE TAG n(x int)")
        c.must("CREATE EDGE e(w int)")
        rows = ", ".join(f"{v}:({v})" for v in range(V))
        c.must(f"INSERT VERTEX n(x) VALUES {rows}")
        rows = ", ".join(f"{s} -> {d}:({s + d})" for s, d in sorted(edges))
        c.must(f"INSERT EDGE e(w) VALUES {rows}")
        conns.append(c)
    cpu, tpuc = conns
    for q in ["FIND ALL PATH FROM 0 TO 7 OVER e UPTO 3 STEPS",
              "FIND NOLOOP PATH FROM 0 TO 7 OVER e UPTO 4 STEPS",
              "FIND ALL PATH FROM 1, 2 TO 9, 11 OVER e UPTO 3 STEPS",
              "FIND SHORTEST PATH FROM 0 TO 13 OVER e UPTO 6 STEPS"]:
        r_cpu = cpu.must(q)
        before = tpu.stats["path_served"]
        r_tpu = tpuc.must(q)
        assert sorted(map(repr, r_cpu.rows)) == \
            sorted(map(repr, r_tpu.rows)), q
        assert tpu.stats["path_served"] > before, q


@pytest.fixture(scope="module")
def pair_dense():
    """Same as `pair` but with the pull-mode budget zeroed, forcing the
    DENSE device dispatch — identity coverage for both halves of the
    direction-optimized engine."""
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    tpu.sparse_edge_budget = 0
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    return cpu_conn, tpu_conn, tpu


@pytest.mark.parametrize("query", EQUALITY_QUERIES)
def test_dense_path_identical_results(pair_dense, query):
    cpu_conn, tpu_conn, tpu = pair_dense
    r_cpu = cpu_conn.must(query)
    r_tpu = tpu_conn.must(query)
    assert r_cpu.columns == r_tpu.columns
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        f"dense-path divergence for: {query}"


def test_dense_mode_really_dense(pair_dense):
    """With the pull budget zeroed, a non-empty GO must take the dense
    device dispatch (a zero-edge frontier may still 'serve' sparsely —
    visiting nothing is under any budget)."""
    _, tpu_conn, tpu = pair_dense
    before = tpu.stats["sparse_served"]
    tpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert tpu.stats["sparse_served"] == before


def test_sparse_path_actually_served(pair):
    """At NBA scale every plain GO fits the pull budget — assert the
    sparse half really is what served."""
    cpu_conn, tpu_conn, tpu = pair
    before = tpu.stats["sparse_served"]
    tpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert tpu.stats["sparse_served"] == before + 1


def test_profile_breakdown_in_response(pair):
    """Device-served queries attach a per-stage breakdown to the
    response (snapshot / kernel / materialize; VERDICT r2 item 9)."""
    cpu_conn, tpu_conn, tpu = pair
    r = tpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert r.profile is not None
    assert r.profile["mode"] in ("sparse", "dense")
    for k in ("snapshot_us", "kernel_us", "materialize_us"):
        assert r.profile[k] >= 0
    # CPU-only statements carry no device profile
    r2 = cpu_conn.must("GO FROM 100 OVER like")
    assert r2.profile is None
    # UPTO and path modes report too
    r3 = tpu_conn.must("GO UPTO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert r3.profile is not None and r3.profile["mode"] in ("upto",
                                                             "sparse")
    r4 = tpu_conn.must(
        "FIND SHORTEST PATH FROM 100 TO 102 OVER like UPTO 4 STEPS")
    assert r4.profile is not None and r4.profile["mode"].startswith("path")


def test_console_profile_toggle(pair):
    import io
    from nebula_tpu.console import Console
    _, tpu_conn, _ = pair
    out = io.StringIO()
    con = Console(tpu_conn, out=out)
    assert con.run_statement(":profile")
    assert con.run_statement("GO FROM 100 OVER like YIELD like._dst")
    text = out.getvalue()
    assert "profile display on" in text
    assert "[tpu " in text and "kernel" in text, text


def test_jax_profiler_trace_produced(pair, tmp_path):
    _, tpu_conn, tpu = pair
    tpu.start_trace(str(tmp_path))
    tpu_conn.must("GO 2 STEPS FROM 100 OVER like")
    tpu.stop_trace()
    files = [p for p in tmp_path.rglob("*") if p.is_file()]
    assert files, "no trace files produced"


def test_sparse_filter_vectorized(pair):
    """WHERE filters on the pull-mode path evaluate as one vectorized
    numpy pass over the active edges (filter_host), not a per-row
    Python walk — and results stay identical to the CPU engine."""
    cpu_conn, tpu_conn, tpu = pair
    for q in [
        "GO FROM 100 OVER like WHERE like.likeness > 85 YIELD like._dst",
        'GO FROM 100 OVER like WHERE $^.player.age > 40 YIELD like._dst',
        'GO FROM 100 OVER like WHERE $$.player.age > 33 && like.likeness '
        ">= 90 YIELD like._dst, like.likeness",
        'GO FROM 100, 101 OVER serve WHERE $$.team.name == "Spurs" '
        "YIELD serve._dst",
        "GO 2 STEPS FROM 100 OVER like WHERE like.likeness + 5 > 95 "
        "YIELD like._dst",
    ]:
        before_v = tpu.stats["host_filter_vectorized"]
        before_s = tpu.stats["sparse_served"]
        r_tpu = tpu_conn.must(q)
        assert tpu.stats["sparse_served"] == before_s + 1, q
        assert tpu.stats["host_filter_vectorized"] == before_v + 1, q
        r_cpu = cpu_conn.must(q)
        assert sorted(map(repr, r_cpu.rows)) == \
            sorted(map(repr, r_tpu.rows)), q


def test_sparse_filter_unsupported_falls_back(pair):
    """A filter outside the vectorizable surface (function call) still
    serves sparsely through the exact per-row walk."""
    cpu_conn, tpu_conn, tpu = pair
    q = ("GO FROM 100 OVER like WHERE abs(like.likeness) > 85 "
         "YIELD like._dst")
    before_v = tpu.stats["host_filter_vectorized"]
    r_tpu = tpu_conn.must(q)
    assert tpu.stats["host_filter_vectorized"] == before_v
    r_cpu = cpu_conn.must(q)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows))


def test_sparse_filter_with_delta_edges(pair):
    """Host-vectorized canonical rows + per-row-filtered delta rows
    agree with the CPU engine after an INSERT lands in the delta."""
    cpu_conn, tpu_conn, tpu = pair
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES '
                  '600:("DeltaGuy", 25)')
        conn.must('INSERT EDGE like(likeness) VALUES 100 -> 600:(99.0)')
    q = "GO FROM 100 OVER like WHERE like.likeness > 90 YIELD like._dst"
    r_cpu = cpu_conn.must(q)
    r_tpu = tpu_conn.must(q)
    assert (600,) in r_tpu.rows
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows))
    for conn in (cpu_conn, tpu_conn):
        conn.must("DELETE VERTEX 600")


def test_dense_delta_filter_vectorized(pair_dense):
    """With delta edges in play the device filter compile is declined
    (_plan_filter) — the dense path must still vectorize the canonical
    filter on host instead of walking rows in Python."""
    cpu_conn, tpu_conn, tpu = pair_dense
    # warm-up: force the snapshot to exist BEFORE the inserts so the
    # writes land in the delta buffer (a cold run would fold them into
    # a fresh canonical build and never exercise the delta-filter path)
    tpu_conn.must("GO FROM 100 OVER like YIELD like._dst")
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES '
                  '601:("DenseDelta", 30)')
        conn.must('INSERT EDGE like(likeness) VALUES 100 -> 601:(97.0)')
    q = "GO FROM 100 OVER like WHERE like.likeness > 90 YIELD like._dst"
    before_v = tpu.stats["host_filter_vectorized"]
    r_tpu = tpu_conn.must(q)
    assert tpu.stats["host_filter_vectorized"] == before_v + 1
    assert (601,) in r_tpu.rows
    r_cpu = cpu_conn.must(q)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows))
    for conn in (cpu_conn, tpu_conn):
        conn.must("DELETE VERTEX 601")


@pytest.fixture(scope="module")
def null_pair():
    """CPU + TPU clusters holding rows with NULL props (written before
    an ALTER added the column) — exercises the null semantics of the
    filter evaluators against the per-row CPU walk."""
    tpu = TpuGraphEngine()
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE ns(partition_num=2)")
        c.must("USE ns")
        c.must("CREATE TAG n(x int)")
        c.must("CREATE EDGE r(w int)")
        c.must('INSERT VERTEX n(x) VALUES 1:(10), 2:(20), 3:(30), 4:(40)')
        c.must("INSERT EDGE r(w) VALUES 1 -> 2:(7), 1 -> 3:(0)")
        # new columns: pre-ALTER rows read as NULL for w2/y
        c.must("ALTER EDGE r ADD (w2 int)")
        c.must("ALTER TAG n ADD (y double)")
        c.must("INSERT EDGE r(w, w2) VALUES 1 -> 4:(5, 50)")
        conns.append(c)
    return conns[0], conns[1], tpu


NULL_SEMANTICS_QUERIES = [
    # null != x -> True; null == x -> False (expressions.py:266-272)
    "GO FROM 1 OVER r WHERE r.w2 != 50 YIELD r._dst",
    "GO FROM 1 OVER r WHERE r.w2 == 50 YIELD r._dst",
    "GO FROM 1 OVER r WHERE r.w2 != 99 YIELD r._dst",
    # ordering ops against null -> False
    "GO FROM 1 OVER r WHERE r.w2 > 0 YIELD r._dst",
    "GO FROM 1 OVER r WHERE !(r.w2 > 0) YIELD r._dst",
    # !null -> True (null is falsy); truthy num in logical ops
    "GO FROM 1 OVER r WHERE !r.w2 YIELD r._dst",
    "GO FROM 1 OVER r WHERE r.w && true YIELD r._dst",
    # null == null -> True (two absent props)
    "GO FROM 1 OVER r WHERE r.w2 == $$.n.y YIELD r._dst",
    # arithmetic on null -> EvalError -> row dropped
    "GO FROM 1 OVER r WHERE r.w2 + 1 > 0 YIELD r._dst",
    # C-style int division + div-by-zero drops the row
    "GO FROM 1 OVER r WHERE r.w / 2 >= 3 YIELD r._dst",
    "GO FROM 1 OVER r WHERE 7 / r.w > 0 YIELD r._dst",
    "GO FROM 1 OVER r WHERE r.w % 4 == 3 YIELD r._dst",
    "GO FROM 1 OVER r WHERE -r.w / 2 == -3 YIELD r._dst",
]


@pytest.mark.parametrize("query", NULL_SEMANTICS_QUERIES)
def test_null_and_division_semantics_sparse(null_pair, query):
    cpu_conn, tpu_conn, tpu = null_pair
    r_cpu = cpu_conn.must(query)
    before = tpu.stats["sparse_served"]
    r_tpu = tpu_conn.must(query)
    assert tpu.stats["sparse_served"] == before + 1, query
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        f"null/division divergence (sparse): {query}"


@pytest.fixture(scope="module")
def null_pair_dense():
    tpu = TpuGraphEngine()
    tpu.sparse_edge_budget = 0
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE nd(partition_num=2)")
        c.must("USE nd")
        c.must("CREATE TAG n(x int)")
        c.must("CREATE EDGE r(w int)")
        c.must('INSERT VERTEX n(x) VALUES 1:(10), 2:(20), 3:(30), 4:(40)')
        c.must("INSERT EDGE r(w) VALUES 1 -> 2:(7), 1 -> 3:(0)")
        c.must("ALTER EDGE r ADD (w2 int)")
        c.must("ALTER TAG n ADD (y double)")
        c.must("INSERT EDGE r(w, w2) VALUES 1 -> 4:(5, 50)")
        conns.append(c)
    return conns[0], conns[1], tpu


@pytest.mark.parametrize("query", NULL_SEMANTICS_QUERIES)
def test_null_and_division_semantics_dense(null_pair_dense, query):
    cpu_conn, tpu_conn, tpu = null_pair_dense
    r_cpu = cpu_conn.must(query)
    r_tpu = tpu_conn.must(query)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows)), \
        f"null/division divergence (dense): {query}"


def test_schema_evolution_yield_identity(null_pair):
    """Rows written before an ALTER decode with their OWN schema
    version in the snapshot (the CPU _decode_row rule): values of
    still-present fields are correct, and YIELD of a field the row's
    version lacks fails the query exactly like the CPU engine."""
    cpu_conn, tpu_conn, tpu = null_pair
    q = "GO FROM 1 OVER r YIELD r._dst, r.w"
    r_cpu = cpu_conn.must(q)
    r_tpu = tpu_conn.must(q)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows))
    assert (2, 7) in r_tpu.rows       # old-version row, real value
    q2 = "GO FROM 1 OVER r YIELD r._dst, r.w2"
    r2_cpu = cpu_conn.execute(q2)
    r2_tpu = tpu_conn.execute(q2)
    assert r2_cpu.code.name == r2_tpu.code.name == "E_EXECUTION_ERROR"


def test_double_filter_exactness_after_alter():
    """Double comparisons must use exact float64 even on shards whose
    columns were built by the python (object-host) path — the float32
    device mirror would round 90.10000001 below 90.1 and drop rows."""
    tpu = TpuGraphEngine()
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE dx(partition_num=2)")
        c.must("USE dx")
        c.must("CREATE TAG n(x int)")
        c.must("CREATE EDGE r(w double)")
        c.must("INSERT VERTEX n(x) VALUES 1:(1), 2:(2), 3:(3)")
        c.must("INSERT EDGE r(w) VALUES 1 -> 2:(90.10000001)")
        c.must("ALTER EDGE r ADD (z int)")   # forces python column build
        c.must("INSERT EDGE r(w, z) VALUES 1 -> 3:(95.5, 1)")
        conns.append(c)
    cpu_conn, tpu_conn = conns
    q = "GO FROM 1 OVER r WHERE r.w > 90.1 YIELD r._dst"
    r_cpu = cpu_conn.must(q)
    r_tpu = tpu_conn.must(q)
    assert sorted(r_cpu.rows) == sorted(r_tpu.rows) == [(2,), (3,)]


def test_batched_count_packed_identity(pair):
    """The bitpacked batched kernel counts exactly what the int8
    variant and per-query multi_hop_count count."""
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse
    _, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")   # force the snapshot
    snap = list(tpu._snapshots.values())[0]
    seeds = [[100], [101, 102], [103, 104, 105], [100, 110]]
    f_batch = jnp.asarray(np.stack(
        [snap.frontier_from_vids(s) for s in seeds]))
    for req_list in ([1], [1, -1], [1, 2]):
        req = jnp.asarray(traverse.pad_edge_types(req_list))
        for steps in (1, 2, 3):
            ak, chunk, group = snap.aligned_kernel()
            packed = np.asarray(traverse.multi_hop_count_batch_packed(
                f_batch, jnp.int32(steps), ak, req, chunk=chunk,
                group=group))
            for i, s in enumerate(seeds):
                single = int(traverse.multi_hop_count(
                    jnp.asarray(snap.frontier_from_vids(s)),
                    jnp.int32(steps), snap.kernel, req))
                assert int(packed[i]) == single, \
                    (req_list, steps, s, packed[i], single)


def test_device_filter_width_and_retype_identity():
    """Identity hazards found in review: int32-wrapping arithmetic and
    out-of-range literals must not be evaluated through the device
    mirrors, and a DROP+ADD retyped field must not break the snapshot
    build (its column goes host-only)."""
    tpu = TpuGraphEngine()
    tpu.sparse_edge_budget = 0     # force the dense device path
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE wd(partition_num=2)")
        c.must("USE wd")
        c.must("CREATE TAG n(age int)")
        c.must("CREATE EDGE r(w int)")
        c.must("INSERT VERTEX n(age) VALUES 1:(40), 2:(20), 3:(30)")
        c.must("INSERT EDGE r(w) VALUES 1 -> 2:(7), 1 -> 3:(3)")
        conns.append(c)
    cpu_conn, tpu_conn = conns
    for q in [
        # int32-wrapping product (4e9 > 2^31)
        "GO FROM 1 OVER r WHERE $^.n.age * 100000000 > 0 YIELD r._dst",
        # literal outside int32 range
        "GO FROM 1 OVER r WHERE r.w < 5000000000 YIELD r._dst",
        # float literal against an int prop
        "GO FROM 1 OVER r WHERE r.w > 2.5 YIELD r._dst",
    ]:
        r_cpu = cpu_conn.must(q)
        r_tpu = tpu_conn.must(q)
        assert sorted(r_cpu.rows) == sorted(r_tpu.rows), q
        assert len(r_tpu.rows) > 0, q   # the guards must not drop rows
    # retype via DROP+ADD: old rows keep int values, new rows string
    for c in (cpu_conn, tpu_conn):
        c.must("ALTER EDGE r DROP (w)")
        c.must("ALTER EDGE r ADD (w string)")
        c.must('INSERT EDGE r(w) VALUES 1 -> 3:("high")')
    q = "GO FROM 1 OVER r YIELD r._dst"
    r_cpu = cpu_conn.must(q)
    r_tpu = tpu_conn.must(q)
    assert sorted(map(repr, r_cpu.rows)) == sorted(map(repr, r_tpu.rows))


def test_native_multi_version_decode_matches_python():
    """Post-ALTER snapshot builds take the per-version-group NATIVE
    decode path; results (values, filters, missing-prop errors) are
    identical to the python multi-version path."""
    from nebula_tpu.engine_tpu import csr as csr_mod
    import nebula_tpu.native as native_mod

    def load(tpu):
        c = InProcCluster(tpu_engine=tpu).connect()
        c.must("CREATE SPACE mvx(partition_num=2)")
        c.must("USE mvx")
        c.must("CREATE TAG n(x int)")
        c.must("CREATE EDGE r(w int, s string)")
        c.must("INSERT VERTEX n(x) VALUES " +
               ", ".join(f"{i}:({i * 2})" for i in range(1, 30)))
        c.must("INSERT EDGE r(w, s) VALUES " +
               ", ".join(f'1 -> {i}:({i}, "a{i % 5}")'
                         for i in range(2, 15)))
        c.must("ALTER EDGE r ADD (z double)")
        c.must("INSERT EDGE r(w, s, z) VALUES " +
               ", ".join(f'1 -> {i}:({i}, "b{i % 3}", {i}.5)'
                         for i in range(15, 30)))
        return c

    calls = {"multi": 0}
    orig = csr_mod._native_build_columns_multi

    def spy(*a, **kw):
        r = orig(*a, **kw)
        if r is not None:
            calls["multi"] += 1
        return r

    csr_mod._native_build_columns_multi = spy
    try:
        c1 = load(TpuGraphEngine())
        queries = [
            "GO FROM 1 OVER r WHERE r.w > 5 YIELD r._dst, r.w, r.s",
            "GO FROM 1 OVER r WHERE r.z > 17 YIELD r._dst, r.z",
            'GO FROM 1 OVER r WHERE r.s == "a2" YIELD r._dst',
        ]
        native_rows = [sorted(map(repr, c1.must(q).rows)) for q in queries]
        err1 = c1.execute("GO FROM 1 OVER r YIELD r.z").code.name
        assert calls["multi"] >= 1, "native multi-version path not taken"
    finally:
        csr_mod._native_build_columns_multi = orig
    avail = native_mod.available
    native_mod.available = lambda: False
    try:
        c2 = load(TpuGraphEngine())
        for q, expect in zip(queries, native_rows):
            assert sorted(map(repr, c2.must(q).rows)) == expect, q
        assert c2.execute("GO FROM 1 OVER r YIELD r.z").code.name == err1 \
            == "E_EXECUTION_ERROR"
    finally:
        native_mod.available = avail


def test_upto_and_roots_filter_vectorized(pair_dense):
    """UPTO and input-ref GO also vectorize non-input WHERE filters on
    the host (compiled once across steps/roots), with delta rows still
    walked per-row — identity against the CPU engine after an INSERT."""
    cpu_conn, tpu_conn, tpu = pair_dense
    tpu_conn.must("GO FROM 100 OVER like YIELD like._dst")  # snapshot up
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES '
                  '602:("UptoDelta", 28)')
        conn.must('INSERT EDGE like(likeness) VALUES 100 -> 602:(93.0)')
    queries = [
        "GO UPTO 2 STEPS FROM 100 OVER like WHERE like.likeness > 90 "
        "YIELD like._dst, like.likeness",
        "GO FROM 100 OVER like YIELD like._dst AS id | "
        "GO FROM $-.id OVER like WHERE like.likeness > 85 "
        "YIELD $-.id AS src, like._dst",
    ]
    for q in queries:
        before_v = tpu.stats["host_filter_vectorized"]
        r_tpu = tpu_conn.must(q)
        assert tpu.stats["host_filter_vectorized"] > before_v, q
        r_cpu = cpu_conn.must(q)
        assert sorted(map(repr, r_cpu.rows)) == \
            sorted(map(repr, r_tpu.rows)), q
    for conn in (cpu_conn, tpu_conn):
        conn.must("DELETE VERTEX 602")


def test_all_paths_random_graph_identity():
    """FIND ALL/NOLOOP/SHORTEST PATH on a ~200-vertex random graph:
    device per-level adjacency + shared enumeration must match the
    CPU executor exactly (VERDICT r2 item 8's larger-graph criterion)."""
    import random
    rnd = random.Random(11)
    n = 200
    edges = sorted({(rnd.randrange(n), rnd.randrange(n))
                    for _ in range(900) if True})
    edges = [(s, d) for s, d in edges if s != d]
    tpu = TpuGraphEngine()
    conns = []
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE rg(partition_num=4)")
        c.must("USE rg")
        c.must("CREATE TAG nn(x int)")
        c.must("CREATE EDGE e(w int)")
        c.must("INSERT VERTEX nn(x) VALUES " +
               ", ".join(f"{i}:({i})" for i in range(n)))
        for i in range(0, len(edges), 400):
            c.must("INSERT EDGE e(w) VALUES " + ", ".join(
                f"{s} -> {d}:({s + d})" for s, d in edges[i:i + 400]))
        conns.append(c)
    cpu, tpuc = conns
    pairs = [(0, 7), (3, 150), (42, 199), (11, 11)]
    for a, b in pairs:
        for form in ("SHORTEST", "ALL", "NOLOOP"):
            k = 3 if form == "ALL" else 4
            q = f"FIND {form} PATH FROM {a} TO {b} OVER e UPTO {k} STEPS"
            r_cpu = cpu.must(q)
            before = tpu.stats["path_served"]
            r_tpu = tpuc.must(q)
            assert sorted(map(repr, r_cpu.rows)) == \
                sorted(map(repr, r_tpu.rows)), q
            assert tpu.stats["path_served"] > before, q


# ---------------------------------------------------------------------------
# device aggregation pushdown: GO | YIELD <aggregates> (bound_stats role)
# ---------------------------------------------------------------------------

AGG_QUERIES = [
    "GO FROM 100 OVER serve YIELD serve.start_year AS y"
    " | YIELD COUNT(*) AS n, SUM($-.y) AS s, AVG($-.y) AS a,"
    " MIN($-.y) AS lo, MAX($-.y) AS hi",
    "GO FROM 100, 101, 102 OVER serve YIELD serve.start_year AS y"
    " | YIELD SUM($-.y), COUNT($-.y)",
    "GO 2 STEPS FROM 100 OVER like YIELD like._dst AS d"
    " | YIELD COUNT(*) AS n",
    "GO FROM 100 OVER serve WHERE serve.start_year > 1995"
    " YIELD serve.start_year AS y | YIELD COUNT(*), SUM($-.y)",
]


@pytest.fixture()
def agg_pair():
    """Function-scoped pair with the dense device path forced (the NBA
    graph is tiny, so the sparse CPU-side pull would otherwise win the
    routing and the pushdown would never trigger)."""
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    tpu.sparse_edge_budget = 0
    return cpu_conn, tpu_conn, tpu, cluster


@pytest.mark.parametrize("query", AGG_QUERIES)
def test_device_aggregate_identity(agg_pair, query):
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    rc, rt = cpu_conn.must(query), tpu_conn.must(query)
    assert rc.columns == rt.columns
    assert rc.rows == rt.rows, (query, rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == 1, (query, tpu.stats)


def test_device_aggregate_empty_results_identical(agg_pair):
    """Empty frontiers (known vid without matching edges, and unknown
    vid) aggregate identically whichever path serves them: COUNT 0,
    SUM/AVG None."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    for q in ("GO FROM 121 OVER serve YIELD serve.start_year AS y"
              " | YIELD COUNT(*), SUM($-.y), AVG($-.y)",
              "GO FROM 999999 OVER serve YIELD serve.start_year AS y"
              " | YIELD COUNT(*), SUM($-.y)"):
        rc, rt = cpu_conn.must(q), tpu_conn.must(q)
        assert rc.rows == rt.rows, (q, rc.rows, rt.rows)
        assert rc.rows[0][0] == 0 and rc.rows[0][1] is None


def test_device_aggregate_declines_double_and_stays_identical(agg_pair):
    """likeness is DOUBLE — outside the int-exact device surface; the
    CPU pipe serves it and results stay identical."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    q = ("GO FROM 100 OVER like YIELD like.likeness AS w"
         " | YIELD SUM($-.w) AS s, COUNT(*) AS n")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == rt.rows
    assert tpu.stats["agg_served"] == 0, tpu.stats


def test_device_aggregate_exact_beyond_int32(agg_pair):
    """The digit-decomposed device sum must be EXACT where a naive
    int32 (or float32) reduction would overflow/round: two int32-max
    start_years sum to 2^32-2."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    big = 2**31 - 1
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES 9901:("B1", 30)')
        conn.must(f"INSERT EDGE serve(start_year, end_year) "
                  f"VALUES 9901 -> 201:({big}, {big})")
        conn.must(f"INSERT EDGE serve(start_year, end_year) "
                  f"VALUES 9901 -> 202:({big}, {big})")
    # writes land in the delta: repack so the canonical block holds them
    q = ("GO FROM 9901 OVER serve YIELD serve.start_year AS y"
         " | YIELD SUM($-.y) AS s, COUNT(*) AS n, AVG($-.y) AS a")
    rc = cpu_conn.must(q)
    assert rc.rows == [(2 * big, 2, float(big))]
    # drop any cached snapshot so the canonical rebuild includes the
    # inserts (delta adds would decline the pushdown)
    tpu._snapshots.clear()
    rt = tpu_conn.must(q)
    assert rt.rows == rc.rows
    assert tpu.stats["agg_served"] == 1, tpu.stats


def test_device_aggregate_declines_on_delta_adds(agg_pair):
    """Buffered delta adds keep the CPU pipe in charge — and identity."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    base = "GO FROM 100 OVER serve YIELD serve.start_year AS y" \
           " | YIELD COUNT(*) AS n, SUM($-.y) AS s"
    tpu_conn.must(base)               # builds the snapshot
    assert tpu.stats["agg_served"] == 1
    for conn in (cpu_conn, tpu_conn):
        conn.must("INSERT EDGE serve(start_year, end_year) "
                  "VALUES 100 -> 202:(2001, 2002)")
    rc, rt = cpu_conn.must(base), tpu_conn.must(base)
    assert rc.rows == rt.rows
    snap = tpu._snapshots.get(list(tpu._snapshots)[0])
    if snap is not None and snap.delta is not None \
            and snap.delta.edge_count > 0:
        assert tpu.stats["agg_served"] == 1, tpu.stats


def test_calibrate_sparse_budget(pair):
    """The measured pull-vs-push crossover replaces the modeled
    constant (round-3 verdict: never validated on hardware) and
    queries keep identical results under the new routing."""
    cpu_conn, tpu_conn, tpu = pair
    tpu_conn.must("GO FROM 100 OVER like")      # build the snapshot
    sid = list(tpu._snapshots)[0]
    before = tpu.sparse_edge_budget
    rec = tpu.calibrate_sparse_budget(sid, [100, 101, 102, 103], [1],
                                      steps=3)
    assert rec is not None
    assert rec["fitted_budget"] == tpu.sparse_edge_budget
    assert rec["probe_edges"] > 0 and rec["sparse_edges_per_sec"] > 0
    assert rec["dense_dispatch_ms"] > 0
    r1 = tpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    r2 = cpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert sorted(map(str, r1.rows)) == sorted(map(str, r2.rows))
    tpu.sparse_edge_budget = before


GROUPED_AGG_QUERIES = [
    "GO FROM 100, 101, 102 OVER like YIELD like._dst AS d"
    " | GROUP BY $-.d YIELD $-.d AS d, COUNT(*) AS n",
    "GO 2 STEPS FROM 100 OVER like YIELD like._dst AS d"
    " | GROUP BY $-.d YIELD COUNT(*) AS n, $-.d AS d",
    "GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t,"
    " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
    " COUNT(*) AS n, SUM($-.y) AS s, MIN($-.y) AS lo, AVG($-.y) AS a",
    "GO FROM 100 OVER serve WHERE serve.start_year > 1995 YIELD"
    " serve._dst AS t, serve.start_year AS y"
    " | GROUP BY $-.t YIELD $-.t AS t, MAX($-.y) AS hi",
]


@pytest.mark.parametrize("query", GROUPED_AGG_QUERIES)
def test_device_grouped_aggregate_identity(agg_pair, query):
    """GROUP BY $-.<dst> served as a device segment reduction keyed by
    the edge's dst slot (the GROUP-BY-COUNT half of the bound_stats
    pushdown, round-3 verdict item 7)."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    rc, rt = cpu_conn.must(query), tpu_conn.must(query)
    assert rc.columns == rt.columns
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
        (query, rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == 1, (query, tpu.stats)


def test_device_grouped_aggregate_empty(agg_pair):
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    q = ("GO FROM 999999 OVER like YIELD like._dst AS d"
         " | GROUP BY $-.d YIELD $-.d AS d, COUNT(*) AS n")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == rt.rows == []


def test_device_grouped_declines_qualified_key_over_multi_types(agg_pair):
    """`serve._dst` as group key under OVER serve, like: the CPU yields
    None for like-edge rows (a None-keyed group) which slot keying
    can't express — the pushdown must decline and identity hold
    (review finding, round 4)."""
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    q = ("GO FROM 100 OVER serve, like YIELD serve._dst AS t"
         " | GROUP BY $-.t YIELD $-.t AS t, COUNT(*) AS n")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
        (rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == 0, tpu.stats
    # unqualified _dst over the same multi-type OVER is exact: serve it
    q2 = ("GO FROM 100 OVER serve, like YIELD _dst AS t"
          " | GROUP BY $-.t YIELD $-.t AS t, COUNT(*) AS n")
    rc2, rt2 = cpu_conn.must(q2), tpu_conn.must(q2)
    assert sorted(map(repr, rc2.rows)) == sorted(map(repr, rt2.rows))
    assert tpu.stats["agg_served"] == 1, tpu.stats


def test_prewarm_builds_snapshot_and_stays_identical():
    """USE kicks a background snapshot build + kernel compile so the
    first big GO doesn't pay the XLA compile; queries before/after are
    unaffected."""
    import time as _t

    _, cpu_conn = load_nba(space="pw_cpu")
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster, space="pw")
    sid = cluster.meta.get_space("pw").value().space_id
    # the USE during load already kicked an async warmup whose install
    # is dropped (data kept changing under it) — drain it, then warm
    # against the now-stable space
    tpu.prewarm(sid, block=True)
    tpu.prewarm(sid, block=True)
    assert sid in tpu._snapshots              # snapshot built off-path
    assert not tpu._prewarming.get(sid)
    r1 = conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    r2 = cpu_conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert sorted(map(str, r1.rows)) == sorted(map(str, r2.rows))
    # USE triggers it too (async): the flag flips or the build finishes
    tpu._snapshots.clear()
    conn.must("USE pw")
    deadline = _t.time() + 15
    while _t.time() < deadline and sid not in tpu._snapshots:
        _t.sleep(0.05)
    assert sid in tpu._snapshots


# ---------------------------------------------------------------------------
# reference-parity: tag-prop defaults for vertices without the tag
# (ref GoTest.cpp:453-465 expects {"Trail Blazers", ""} etc., via
# VertexHolder::get -> RowReader::getDefaultProp; unknown props stay
# errors, GoTest NotExistTagProp :683-698)
# ---------------------------------------------------------------------------

def test_tag_default_semantics_reference_parity(pair):
    cpu_conn, tpu_conn, tpu = pair
    # mixed dst kinds: teams have no player tag and vice versa — the
    # reference yields type defaults ("" / 0), not an error
    q = "GO FROM 100 OVER * YIELD $$.team.name, $$.player.name"
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert any(row[0] == "" for row in rc.rows)       # like-edges: no team
    assert any(row[1] == "" for row in rc.rows)       # serve-edges: no player
    q2 = "GO FROM 100 OVER like YIELD like._dst, $$.team.name"
    rc2, rt2 = cpu_conn.must(q2), tpu_conn.must(q2)
    assert sorted(map(repr, rc2.rows)) == sorted(map(repr, rt2.rows))
    assert all(row[1] == "" for row in rc2.rows)
    # int default is 0 — and WHERE compares against it (players have
    # no team tag; serve dsts have no player tag -> age reads 0)
    q3 = ("GO FROM 100 OVER serve WHERE $$.player.age < 33 "
          "YIELD serve._dst")
    rc3, rt3 = cpu_conn.must(q3), tpu_conn.must(q3)
    assert sorted(rc3.rows) == sorted(rt3.rows)
    assert rc3.rows, "default 0 < 33 should keep the team rows"
    # unknown prop on a KNOWN tag stays a query error (NotExistTagProp)
    for q4 in ("GO FROM 100 OVER serve YIELD $^.player.nope",
               "GO FROM 100 OVER serve YIELD $$.team.nope"):
        r_c, r_t = cpu_conn.execute(q4), tpu_conn.execute(q4)
        assert not r_c.ok() and not r_t.ok(), q4


def test_dangling_dst_defaults_and_traversal(pair):
    """Edges to vids never inserted as vertices: traversal includes
    them (edge keys are the truth) and their $$ props read as schema
    defaults on both engines."""
    cpu_conn, tpu_conn, tpu = pair
    for conn in (cpu_conn, tpu_conn):
        conn.must("INSERT EDGE like(likeness) VALUES 100 -> 888777:(50.0)")
    q = "GO FROM 100 OVER like YIELD like._dst, $$.player.name"
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert (888777, "") in rc.rows
    for conn in (cpu_conn, tpu_conn):   # restore fixture data
        conn.must("DELETE EDGE like 100 -> 888777")


def test_ttl_identity_on_device():
    """TTL'd tag and edge rows: expired edges are invisible to the
    device traversal and expired tag rows read as schema defaults —
    identical to the CPU engine (TTL visibility applies at snapshot
    build, matching what the CPU scan sees at query time)."""
    import time as _t

    now = int(_t.time())
    stale, fresh = now - 5000, now
    conns = []
    tpu = TpuGraphEngine()
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        c = cluster.connect()
        c.must("CREATE SPACE ttl_dev(partition_num=2)")
        c.must("USE ttl_dev")
        c.must("CREATE TAG mark(score int, ts timestamp) "
               "ttl_duration = 1000, ttl_col = ts")
        c.must("CREATE EDGE rel(w int, ts timestamp) "
               "ttl_duration = 1000, ttl_col = ts")
        c.must(f"INSERT VERTEX mark(score, ts) VALUES "
               f"1:(11, {fresh}), 2:(22, {stale}), 3:(33, {fresh}), "
               f"4:(44, {stale})")
        c.must(f"INSERT EDGE rel(w, ts) VALUES "
               f"1 -> 2:(12, {fresh}), 1 -> 3:(13, {stale}), "
               f"2 -> 4:(24, {fresh}), 3 -> 4:(34, {fresh})")
        conns.append(c)
    cpu_conn, tpu_conn = conns
    for q in ("GO FROM 1 OVER rel YIELD rel._dst",          # 1->3 expired
              "GO 2 STEPS FROM 1 OVER rel YIELD rel._dst",
              "GO FROM 1 OVER rel YIELD rel._dst, $$.mark.score",
              "GO FROM 1, 2, 3 OVER rel WHERE $$.mark.score > 0 "
              "YIELD rel._dst, $$.mark.score"):
        rc, rt = cpu_conn.must(q), tpu_conn.must(q)
        assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
            (q, rc.rows, rt.rows)
    # the expired edge really is gone, and the expired dst tag row
    # (vid 2, stale) reads as default 0 on both engines
    r = cpu_conn.must("GO FROM 1 OVER rel YIELD rel._dst, $$.mark.score")
    assert sorted(r.rows) == [(2, 0)]
    assert tpu.stats["go_served"] >= 4
    # expired REVERSE copies are invisible too
    for q in ("GO FROM 3 OVER rel REVERSELY YIELD rel._dst",
              "GO FROM 4 OVER rel REVERSELY YIELD rel._dst"):
        rc, rt = cpu_conn.must(q), tpu_conn.must(q)
        assert sorted(rc.rows) == sorted(rt.rows), (q, rc.rows, rt.rows)
    # TTL'd edges arriving through the DELTA buffer behave the same
    for c in (cpu_conn, tpu_conn):
        c.must(f"INSERT EDGE rel(w, ts) VALUES 1 -> 4:(14, {stale})")
        c.must(f"INSERT EDGE rel(w, ts) VALUES 3 -> 1:(31, {fresh})")
    rc = cpu_conn.must("GO FROM 1, 3 OVER rel YIELD rel._dst, rel.w")
    rt = tpu_conn.must("GO FROM 1, 3 OVER rel YIELD rel._dst, rel.w")
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert (1, 31) in rc.rows and (4, 14) not in rc.rows


# ---------------------------------------------------------------------------
# sparse aggregation: small frontiers reduced over the pull set instead
# of declining to the CPU pipe (round-4 verdict item 2)
# ---------------------------------------------------------------------------

@pytest.fixture()
def sparse_agg_pair():
    """Like agg_pair but with the DEFAULT pull budget, so the tiny NBA
    graph routes every aggregate through the sparse host reduction."""
    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, tpu_conn = load_nba(cluster)
    return cpu_conn, tpu_conn, tpu, cluster


@pytest.mark.parametrize("query", AGG_QUERIES + GROUPED_AGG_QUERIES)
def test_sparse_aggregate_identity(sparse_agg_pair, query):
    """Every dense-path aggregate query also serves (identically)
    through the sparse reduction when the frontier is small — the
    routing the round-4 bench showed declining 3/3 queries."""
    cpu_conn, tpu_conn, tpu, _ = sparse_agg_pair
    rc, rt = cpu_conn.must(query), tpu_conn.must(query)
    assert rc.columns == rt.columns
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
        (query, rc.rows, rt.rows)
    assert tpu.stats["agg_served"] == 1, (query, tpu.stats)
    assert tpu.stats["agg_sparse_served"] == 1, (query, tpu.stats)


def test_sparse_aggregate_serves_delta_adds(sparse_agg_pair):
    """Unlike the dense device reduction, the sparse path folds
    delta-buffer rows into the reduction — buffered adds no longer
    force the CPU pipe."""
    cpu_conn, tpu_conn, tpu, _ = sparse_agg_pair
    q = ("GO FROM 100 OVER serve YIELD serve.start_year AS y"
         " | YIELD COUNT(*) AS n, SUM($-.y) AS s, MIN($-.y) AS lo")
    tpu_conn.must(q)              # builds the snapshot
    assert tpu.stats["agg_sparse_served"] == 1
    for conn in (cpu_conn, tpu_conn):
        conn.must("INSERT EDGE serve(start_year, end_year) "
                  "VALUES 100 -> 202:(2001, 2002)")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == rt.rows, (rc.rows, rt.rows)
    sid = list(tpu._snapshots)[0]
    snap = tpu._snapshots[sid]
    assert snap.delta is not None and snap.delta.edge_count > 0, \
        "test must exercise the delta-fold path"
    assert tpu.stats["agg_sparse_served"] == 2, tpu.stats
    # grouped twin over the same delta state
    qg = ("GO FROM 100 OVER serve YIELD serve._dst AS t,"
          " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
          " COUNT(*) AS n, SUM($-.y) AS s")
    rcg, rtg = cpu_conn.must(qg), tpu_conn.must(qg)
    assert sorted(map(repr, rcg.rows)) == sorted(map(repr, rtg.rows)), \
        (rcg.rows, rtg.rows)
    assert tpu.stats["agg_sparse_served"] == 3, tpu.stats


def test_sparse_aggregate_exact_beyond_int32(sparse_agg_pair):
    """The hi/lo-split host sum stays exact where float64 or int32
    accumulation would not."""
    cpu_conn, tpu_conn, tpu, _ = sparse_agg_pair
    big = 2**31 - 1
    for conn in (cpu_conn, tpu_conn):
        conn.must('INSERT VERTEX player(name, age) VALUES 9901:("B1", 30)')
        for dst in (201, 202, 203):
            conn.must(f"INSERT EDGE serve(start_year, end_year) "
                      f"VALUES 9901 -> {dst}:({big}, {big})")
    q = ("GO FROM 9901 OVER serve YIELD serve.start_year AS y"
         " | YIELD SUM($-.y) AS s, COUNT(*) AS n, AVG($-.y) AS a")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == [(3 * big, 3, float(big))]
    assert rt.rows == rc.rows
    assert tpu.stats["agg_sparse_served"] == 1, tpu.stats


def test_agg_decline_reasons_counted(sparse_agg_pair):
    """Round-4 verdict: declines were invisible. Every decline now
    lands in agg_decline_reasons (and the global stats manager that
    /get_stats serves)."""
    from nebula_tpu.common.stats import stats as global_stats
    cpu_conn, tpu_conn, tpu, _ = sparse_agg_pair
    before = global_stats.read_stats(
        "tpu_engine.agg_declined.non_int_prop.sum.600")
    q = ("GO FROM 100 OVER like YIELD like.likeness AS w"
         " | YIELD SUM($-.w) AS s")          # DOUBLE prop: declined
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert rc.rows == rt.rows
    assert tpu.stats["agg_served"] == 0
    assert tpu.stats["agg_declined"] >= 1
    assert tpu.agg_decline_reasons.get("non_int_prop", 0) >= 1, \
        tpu.agg_decline_reasons
    after = global_stats.read_stats(
        "tpu_engine.agg_declined.non_int_prop.sum.600")
    assert (after or 0) > (before or 0)


def test_grouped_reduce_chunked_exact():
    """SUM/AVG past MAX_GROUPED_SUM_ROWS switch to chunked digit
    partials with host int64 accumulation instead of declining
    (round-4 verdict weak #6): a >2^23-masked-row grouped SUM must be
    bit-exact against the numpy int64 reference."""
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import aggregate

    n = aggregate.MAX_GROUPED_SUM_ROWS + (1 << 20)     # 9.4M rows
    rng = np.random.default_rng(3)
    vals = rng.integers(-(2**31), 2**31, n, dtype=np.int64).astype(np.int32)
    groups = rng.integers(0, 4, n).astype(np.int32)
    mask = rng.random(n) < 0.9

    class _V:
        pass

    v = _V()
    v.value = jnp.asarray(vals.reshape(1, -1))
    v.null = jnp.zeros((1, n), bool)
    active = jnp.asarray(mask.reshape(1, -1))
    gidx = jnp.asarray(groups.reshape(1, -1))
    got_groups, cols = aggregate.grouped_reduce(
        [("SUM", "k"), ("COUNT", None), ("AVG", "k")], active, {"k": v},
        gidx, 4)
    # int64 numpy reference (n * |v| < 2^63 here, so int64 is exact)
    ref_sum = [int(vals[mask & (groups == g)].astype(np.int64).sum())
               for g in got_groups]
    ref_cnt = [int((mask & (groups == g)).sum()) for g in got_groups]
    assert list(cols[0]) == ref_sum
    assert list(cols[1]) == ref_cnt
    assert list(cols[2]) == [s / c for s, c in zip(ref_sum, ref_cnt)]


@pytest.mark.parametrize("native", [False, True])
def test_alter_ttl_identity_on_device(native):
    """TTL added by ALTER: old-version edge rows WITHOUT the ttl col
    stay visible forever (CPU: the row's own schema version has no
    ttl_col, processors.py _decode_row) while post-ALTER stale rows
    expire — identical on the device for BOTH shard builders. The
    packed builder used to mark version-missing ttl cells dead
    (advisor finding r4, csr.py:574); the native-extract builder used
    to skip edge TTL invalidation entirely."""
    if native:
        from nebula_tpu import native as native_mod
        if not native_mod.available():
            pytest.skip("native library unavailable")
        from nebula_tpu.kvstore.nativeengine import NativeEngine
    import time as _t

    now = int(_t.time())
    stale, fresh = now - 5000, now
    conns = []
    tpu = TpuGraphEngine()
    for cluster in (InProcCluster(), InProcCluster(tpu_engine=tpu)):
        if native:
            cluster.store._engine_factory = lambda sid: NativeEngine()
        c = cluster.connect()
        c.must("CREATE SPACE attl(partition_num=2)")
        c.must("USE attl")
        c.must("CREATE EDGE rel(w int)")
        c.must("INSERT EDGE rel(w) VALUES 1 -> 2:(12), 1 -> 3:(13)")
        c.must("ALTER EDGE rel ADD (ts timestamp) "
               "TTL_DURATION = 1000, TTL_COL = ts")
        c.must(f"INSERT EDGE rel(w, ts) VALUES 1 -> 4:(14, {fresh}), "
               f"1 -> 5:(15, {stale})")
        conns.append(c)
    cpu_conn, tpu_conn = conns
    for q in ("GO FROM 1 OVER rel YIELD rel._dst",
              "GO FROM 1 OVER rel YIELD rel._dst, rel.w"):
        rc, rt = cpu_conn.must(q), tpu_conn.must(q)
        assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows)), \
            (q, rc.rows, rt.rows)
    # v0 rows (no ts) + the fresh v1 row are visible; the stale v1 row
    # expired on both engines
    r = cpu_conn.must("GO FROM 1 OVER rel YIELD rel._dst")
    assert sorted(r.rows) == [(2,), (3,), (4,)], r.rows
    assert tpu.stats["go_served"] >= 2
    # harder case (review finding r5): v0 has NO fields at all, so v0
    # rows share no decoded column with the post-ALTER schema — they
    # must STILL stay visible forever (CPU: v0 schema has no ttl_col)
    for c in conns:
        c.must("CREATE EDGE bare()")
        c.must("INSERT EDGE bare() VALUES 1 -> 7:()")
        c.must("ALTER EDGE bare ADD (ts timestamp) "
               "TTL_DURATION = 1000, TTL_COL = ts")
        c.must(f"INSERT EDGE bare(ts) VALUES 1 -> 8:({fresh}), "
               f"1 -> 9:({stale})")
    q = "GO FROM 1 OVER bare YIELD bare._dst"
    rc, rt = conns[0].must(q), conns[1].must(q)
    assert sorted(rc.rows) == sorted(rt.rows) == [(7,), (8,)], \
        (rc.rows, rt.rows)


def test_prewarm_auto_calibrates_budget():
    """Round-4 verdict item 4: production engines must not keep the
    modeled default crossover — the prewarm hook (fired by USE)
    calibrates a measured per-space budget; explicit assignment pins
    routing and disables/clears auto-calibration."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster)
    sid = cluster.meta.get_space("nba").value().space_id
    tpu.prewarm(sid, block=True)
    rec = tpu.sparse_budget_calibrations.get(sid)
    assert rec is not None, "prewarm must calibrate the space budget"
    assert tpu._space_budgets[sid] == rec["fitted_budget"]
    assert rec["fitted_budget"] >= 1 << 14 and rec["probe_edges"] > 0
    # the fit is visible through the stats manager (/get_stats)
    from nebula_tpu.common.stats import stats as global_stats
    assert global_stats.read_stats(
        "tpu_engine.sparse_budget_fit.sum.600") >= rec["fitted_budget"]
    # identity under the calibrated routing
    rc = conn.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
    assert rc.rows
    # explicit pin wins: per-space fits drop, auto-calibration stops
    tpu.sparse_edge_budget = 0
    assert tpu._space_budgets == {}
    tpu.sparse_budget_calibrations.clear()
    tpu.prewarm(sid, block=True)
    assert tpu.sparse_budget_calibrations == {}
    assert tpu.sparse_edge_budget == 0


def test_cross_session_batched_dispatch_identity():
    """Round-4 verdict item 3: concurrent sessions' dense GOs coalesce
    into shared [N, P, cap_v] device programs (group commit). Results
    must be identical to the serial CPU path, errors must stay
    per-query, and a pile-up during one round must coalesce into the
    next round's batch."""
    import threading
    import time as _t

    _, cpu_conn = load_nba()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, warm = load_nba(cluster)
    tpu.sparse_edge_budget = 0      # pin: every GO rides the dense path
    queries = [
        "GO 2 STEPS FROM 100 OVER like YIELD like._dst",
        "GO FROM 101 OVER like YIELD like._dst",
        "GO 2 STEPS FROM 102 OVER like YIELD like._dst, $$.player.name",
        "GO FROM 100 OVER like WHERE like.likeness > 80 "
        "YIELD like._dst",
    ]
    expected = {q: sorted(map(repr, cpu_conn.must(q).rows))
                for q in queries}
    warm.must(queries[0])           # snapshot + XLA compile up front
    # force-build the aligned layout so multi-query rounds take the
    # lane-matrix batched kernel (prewarm builds it in production;
    # the test must not race that background thread)
    sid = cluster.meta.get_space("nba").value().space_id
    tpu.snapshot(sid).aligned_kernel()
    # slow the serve step so a round in flight lets the other threads
    # pile into the queue — the NEXT round must then coalesce them
    orig = tpu._serve_batch

    def slow_serve(batch, ex):
        _t.sleep(0.03)
        orig(batch, ex)

    tpu._serve_batch = slow_serve
    n_threads = 8
    barrier = threading.Barrier(n_threads)
    errs = []

    def worker(k):
        conn = cluster.connect()
        conn.must("USE nba")
        barrier.wait()
        for i in range(4):
            q = queries[(k + i) % len(queries)]
            try:
                r = conn.must(q)
                if sorted(map(repr, r.rows)) != expected[q]:
                    errs.append((q, r.rows))
            except Exception as e:      # noqa: BLE001
                errs.append((q, repr(e)))

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:3]
    st = tpu.stats
    # every query device-served; the coalesced ones (multi-member
    # groups) shared dispatches — single-member rounds take the plain
    # path and don't count as batched
    assert st["go_served"] >= n_threads * 4, st
    assert st["batched_max_window"] >= 2, st
    assert st["batched_dispatches"] < st["batched_queries"], st
    # multi-query rounds rode the shared lane-matrix kernel
    assert st["batched_lane_rounds"] >= 1, st


def test_grouped_chunked_stat_fires(agg_pair, monkeypatch):
    """Past the single-pass digit bound the grouped reduction switches
    to chunked partials and COUNTS it (round-4 verdict weak #6: the
    fallback was silent) — forced here by shrinking the bound."""
    from nebula_tpu.engine_tpu import aggregate
    cpu_conn, tpu_conn, tpu, _ = agg_pair
    monkeypatch.setattr(aggregate, "MAX_GROUPED_SUM_ROWS", 1)
    q = ("GO FROM 100, 101, 102 OVER serve YIELD serve._dst AS t,"
         " serve.start_year AS y | GROUP BY $-.t YIELD $-.t AS t,"
         " SUM($-.y) AS s")
    rc, rt = cpu_conn.must(q), tpu_conn.must(q)
    assert sorted(map(repr, rc.rows)) == sorted(map(repr, rt.rows))
    assert tpu.stats.get("agg_grouped_chunked", 0) == 1, tpu.stats
    from nebula_tpu.common.stats import stats as global_stats
    assert global_stats.read_stats(
        "tpu_engine.agg_grouped_chunked.sum.600") >= 1


# ---------------------------------------------------------------------------
# ISSUE 1: GIL-free batch materialization + group-complete dispatcher
# ---------------------------------------------------------------------------

def test_mixed_key_dispatcher_group_complete():
    """Acceptance: heterogeneous (space, steps, edge_types) groups
    under concurrent load are INDEPENDENT rounds — a waiter wakes when
    its own group completes and its wall time is never bounded by an
    unrelated slow group (pre-rework: one global round served all
    groups serially, so the 1-step query below would have waited out
    the slow 2-step window)."""
    import threading
    import time as _t

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, warm = load_nba(cluster)
    # warm both keys' snapshots/compiles so timings measure scheduling
    warm.must("GO FROM 100 OVER like YIELD like._dst")
    warm.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")

    SLOW = 1.0
    slow_started = threading.Event()
    orig = tpu._serve_batch

    def gated(batch, ex):
        if batch[0].key[1] == 2:       # the slow (2-step) group only
            slow_started.set()
            _t.sleep(SLOW)
        orig(batch, ex)

    tpu._serve_batch = gated
    results = {}
    errs = []

    def run_slow():
        try:
            c = cluster.connect()
            c.must("USE nba")
            t0 = _t.monotonic()
            c.must("GO 2 STEPS FROM 100 OVER like YIELD like._dst")
            results["slow"] = _t.monotonic() - t0
        except Exception as e:          # noqa: BLE001
            errs.append(repr(e))

    def run_fast():
        try:
            c = cluster.connect()
            c.must("USE nba")
            assert slow_started.wait(10), "slow round never started"
            t0 = _t.monotonic()
            c.must("GO FROM 101 OVER like YIELD like._dst")
            results["fast"] = _t.monotonic() - t0
        except Exception as e:          # noqa: BLE001
            errs.append(repr(e))

    ts = threading.Thread(target=run_slow)
    tf = threading.Thread(target=run_fast)
    ts.start(); tf.start(); ts.join(); tf.join()
    tpu._serve_batch = orig
    assert not errs, errs
    # the fast group's waiter completed INSIDE the slow group's round:
    # group-complete wakeup, not end-of-round
    assert results["fast"] < SLOW / 2, results
    assert results["slow"] >= SLOW, results
    # the fast group's leader took over while the slow round was in
    # flight — a cross-group handoff
    assert tpu.stats["leader_handoffs"] >= 1, tpu.stats


def test_deferred_native_encode_identity_and_fallback(monkeypatch):
    """Acceptance: the deferred (window-encoded) materialization path
    produces byte-identical rows through the native encoder AND the
    pure-Python fallback, and both match the CPU path."""
    import nebula_tpu.native as native_mod
    from nebula_tpu.native import NativeBuildError

    q = "GO 2 STEPS FROM 100 OVER like YIELD like._dst, like.likeness"
    _, cpu_conn = load_nba()
    expected = sorted(map(repr, cpu_conn.must(q).rows))

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster)
    r = conn.must(q)
    assert sorted(map(repr, r.rows)) == expected
    assert tpu.stats["native_encode_rows"] > 0, tpu.stats
    assert tpu.stats["fast_materialize"] > 0, tpu.stats

    # force the pure-Python fallback encoder: rows must stay identical
    def boom(*a, **k):
        raise NativeBuildError("forced fallback for test")
    monkeypatch.setattr(native_mod, "encode_rows", boom)
    tpu2 = TpuGraphEngine()
    cluster2 = InProcCluster(tpu_engine=tpu2)
    _, conn2 = load_nba(cluster2)
    r2 = conn2.must(q)
    assert sorted(map(repr, r2.rows)) == expected
    assert tpu2.stats["encode_fallback_rows"] > 0, tpu2.stats


def test_calibrate_pin_not_overridden_mid_probe():
    """Satellite: an explicit sparse_edge_budget pin landing while an
    auto-calibration probe is mid-flight can no longer be silently
    overridden — the pinned-check and the install are one critical
    section (and the setter takes the same lock)."""
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster)
    conn.must("GO FROM 100 OVER like YIELD like._dst")   # build snapshot
    sid = cluster.meta.get_space("nba").value().space_id
    etype = cluster.sm.edge_type(sid, "like")

    orig = tpu._sparse_expand

    def pin_mid_probe(snap, starts, edge_types, steps, budget=None):
        # an operator pin arriving DURING the calibration walk (the
        # engine RLock is re-entrant, so this models a pin that wins
        # the lock between the probe and the install)
        tpu.sparse_edge_budget = 12345
        return orig(snap, starts, edge_types, steps, budget=budget)

    tpu._sparse_expand = pin_mid_probe
    try:
        rec = tpu.calibrate_sparse_budget(sid, [100, 101], [etype],
                                          steps=2, auto=True)
    finally:
        tpu._sparse_expand = orig
    assert rec is None
    assert tpu.sparse_edge_budget == 12345
    assert tpu._budget_pinned
    assert tpu._space_budgets == {}


def test_can_serve_path_prechecks_cost_no_snapshot():
    """Satellite: a FIND ALL PATH the device path would decline anyway
    (steps out of the device range) is routed to the CPU BEFORE the
    engine lock + snapshot are taken, and the decline is counted."""
    q = "FIND ALL PATH FROM 100 TO 102 OVER like UPTO 0 STEPS"
    _, cpu_conn = load_nba()
    expected = sorted(map(repr, cpu_conn.must(q).rows))

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster)
    snapshot_calls = []
    orig = tpu._snapshot_locked
    tpu._snapshot_locked = lambda sid: (snapshot_calls.append(sid),
                                        orig(sid))[1]
    try:
        r = conn.must(q)
    finally:
        tpu._snapshot_locked = orig
    assert sorted(map(repr, r.rows)) == expected
    assert snapshot_calls == [], "decline paid a snapshot acquisition"
    assert tpu.stats["path_declined"] >= 1, tpu.stats
    assert tpu.path_decline_reasons.get(
        "all_paths_steps_out_of_range", 0) >= 1, tpu.path_decline_reasons
    from nebula_tpu.common.stats import stats as global_stats
    assert global_stats.read_stats(
        "tpu_engine.path_declined.all_paths_steps_out_of_range.sum.600") >= 1


def test_grouped_count_chunked_exact(monkeypatch):
    """Satellite: grouped COUNT / non-null scatter-adds chunk past
    COUNT_CHUNK slots with host int64 accumulation (the old single
    int32 pass silently wrapped past 2^31 rows) — forced here by
    shrinking the chunk, checked against numpy bincount."""
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import aggregate
    monkeypatch.setattr(aggregate, "COUNT_CHUNK", 7)
    rng = np.random.default_rng(11)
    n, n_groups = 53, 6
    g_np = rng.integers(0, n_groups, n).astype(np.int32)
    m_np = rng.integers(0, 2, n).astype(bool)
    out = aggregate._scatter_count_i64(jnp.asarray(m_np),
                                       jnp.asarray(g_np), n_groups)
    ref = np.bincount(g_np[m_np], minlength=n_groups)
    assert out.dtype == np.int64
    assert (out == ref).all(), (out, ref)


def test_batched_kernel_calibration_runs_once_and_keeps_identity():
    """The first multi-member window measures lane-matrix vs vmapped
    batched kernels and caches the pick on the snapshot (fallback
    backends can be several times faster on the vmapped variant);
    results stay identical either way and the record is
    operator-visible."""
    import threading

    q = "GO 2 STEPS FROM 100 OVER like YIELD like._dst"
    _, cpu_conn = load_nba()
    expected = sorted(map(repr, cpu_conn.must(q).rows))

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, warm = load_nba(cluster)
    tpu.sparse_edge_budget = 0      # dense: dispatcher windows
    warm.must(q)
    sid = cluster.meta.get_space("nba").value().space_id
    tpu.snapshot(sid).aligned_kernel()

    # stall one round so a multi-member window forms behind it
    orig = tpu._serve_batch

    def slow(batch, ex):
        import time as _t
        _t.sleep(0.05)
        orig(batch, ex)

    tpu._serve_batch = slow
    errs = []

    def worker():
        try:
            c = cluster.connect()
            c.must("USE nba")
            for _ in range(3):
                r = c.must(q)
                if sorted(map(repr, r.rows)) != expected:
                    errs.append(r.rows)
        except Exception as e:      # noqa: BLE001
            errs.append(repr(e))

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tpu._serve_batch = orig
    assert not errs, errs[:3]
    rec = tpu.batched_kernel_calibrations.get(sid)
    assert rec is not None and rec["pick"] in ("lane", "vmap"), rec
    assert rec["lane_ms"] > 0 and rec["vmap_ms"] > 0, rec
    snap = tpu.snapshot(sid)
    assert getattr(snap, "batched_kernel_pick", None) == rec["pick"]
