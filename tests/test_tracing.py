"""End-to-end query tracing (common/tracing.py +
docs/manual/10-observability.md): span trees, head sampling, the
PROFILE statement, trace-context propagation over the RPC envelope
(incl. retry/reconnect), the slow/active-query surfaces, ring bounds,
and the kind-aware StatsManager snapshot."""
import json
import threading
import time
import urllib.request

import pytest

from nebula_tpu.common.flags import graph_flags
from nebula_tpu.common.stats import StatsManager
from nebula_tpu.common.tracing import (ActiveQueryRegistry, SlowQueryLog,
                                       TraceRing, Tracer, render_tree,
                                       stage_breakdown, tracer)


# ---------------------------------------------------------------- unit

def test_unsampled_spans_are_noops():
    t = Tracer()
    assert not t.active()
    with t.span("anything", k=1) as sp:
        sp.tag("x", 2)          # must not explode
        t.tag_root("deg", "y")
        t.add_span("kernel", 123.0)
    assert len(t.ring) == 0
    assert t.current_ctx() is None


def test_trace_tree_nesting_and_render():
    t = Tracer()
    h = t.begin("query", force=True)
    with t.span("parse"):
        pass
    with t.span("exec.go"):
        with t.span("kernel", mode="dense"):
            time.sleep(0.001)
        t.add_span("encode", 500.0, rows=3)
        t.tag("served", True)
    t.tag_root("feature", "go")
    trace = h.finish(ok=True)
    assert trace is not None and len(t.ring) == 1
    assert t.ring.get(trace["trace_id"]) == trace
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["kernel"]["parent_id"] == by_name["exec.go"]["span_id"]
    assert by_name["encode"]["parent_id"] == by_name["exec.go"]["span_id"]
    assert by_name["parse"]["parent_id"] == by_name["query"]["span_id"]
    assert by_name["kernel"]["dur_us"] >= 1000
    assert by_name["encode"]["dur_us"] == 500
    assert by_name["exec.go"]["tags"]["served"] is True
    assert trace["tags"]["feature"] == "go"
    rows = render_tree(trace)
    assert rows[0][0] == "query"
    names = [r[0] for r in rows]
    assert ". . kernel" in names and ". parse" in names
    # after finish the thread is detached
    assert not t.active()


def test_sampling_rate_and_arm_knob():
    t = Tracer()
    t.sample_rate = 0.0
    assert not t.begin("q").sampled           # null handle, no ctx set
    assert not t.active()
    t.sample_rate = 1.0
    h = t.begin("q")
    assert h.sampled
    h.finish()
    t.sample_rate = 0.0
    # the X-Trace arm knob fires exactly N forced samples
    assert t.arm(2) == 2
    fired = []
    for _ in range(4):
        h2 = t.begin("q")
        fired.append(h2.sampled)
        h2.finish()
    assert fired == [True, True, False, False]
    assert not t.active()


def test_ring_bounds_and_filters():
    ring = TraceRing(maxlen=4)
    for i in range(10):
        ring.add({"trace_id": f"t{i}", "name": "query",
                  "t0_us": i, "dur_us": i * 1000,
                  "tags": {"feature": "go" if i % 2 else "use"},
                  "spans": []})
    assert len(ring) == 4                      # bounded
    assert ring.get("t0") is None              # evicted
    lst = ring.list()
    assert [t["trace_id"] for t in lst] == ["t9", "t8", "t7", "t6"]
    assert all(t["tags"]["feature"] == "go"
               for t in ring.list(feature="go"))
    assert [t["trace_id"] for t in ring.list(min_dur_us=9000)] == ["t9"]
    assert len(ring.list(limit=2)) == 2


def test_slow_log_and_active_registry():
    slow = SlowQueryLog(maxlen=3)
    for i in range(5):
        slow.add(f"GO {i}", latency_us=1000 * i, session=i)
    snap = slow.snapshot()
    assert len(snap) == 3 and snap[0]["stmt"] == "GO 4"   # newest first
    reg = ActiveQueryRegistry()
    tok = reg.register("GO FROM 1", session=7, user="root")
    time.sleep(0.002)
    view = reg.snapshot()
    assert len(view) == 1 and view[0]["stmt"] == "GO FROM 1"
    assert view[0]["elapsed_ms"] > 0 and view[0]["session"] == 7
    reg.unregister(tok)
    assert reg.snapshot() == [] and reg.count() == 0


def test_stage_breakdown():
    traces = [{"spans": [
        {"name": "kernel", "dur_us": d, "span_id": "", "parent_id": "",
         "t0_us": 0, "tags": {}},
        {"name": "materialize", "dur_us": d * 2, "span_id": "",
         "parent_id": "", "t0_us": 0, "tags": {}}]}
        for d in (100, 200, 300)]
    out = stage_breakdown(traces)
    assert out["kernel"]["n"] == 3 and out["kernel"]["p50_us"] == 200
    assert out["materialize"]["p95_us"] == 600
    assert out["dispatcher_wait"]["n"] == 0


# ------------------------------------------------ stats kinds (satellite)

def test_stats_kind_aware_snapshot_and_prometheus():
    clock = [1000.0]
    sm = StatsManager(clock=lambda: clock[0])
    sm.add_value("reqs", kind="counter")
    sm.add_value("reqs", kind="counter")
    sm.add_value("lat_us", 100.0, kind="timing")
    sm.add_value("lat_us", 300.0, kind="timing")
    sm.add_value("legacy", 5.0)
    snap = sm.snapshot()
    # counters: no meaningless distribution methods
    assert "reqs.sum.60" in snap and snap["reqs.sum.60"] == 2.0
    assert "reqs.p95.60" not in snap and "reqs.avg.60" not in snap
    # timings: distribution methods present
    assert "lat_us.p95.60" in snap and "lat_us.avg.60" in snap
    assert snap["lat_us.avg.60"] == 200.0
    # untagged keeps the legacy emit-everything behavior
    assert "legacy.p95.60" in snap and "legacy.sum.60" in snap
    # read_stats stays spec-compatible for ANY kind
    assert sm.read_stats("reqs.p99.60") is not None
    assert sm.read_stats("reqs.count.60") == 2.0
    # prometheus: counters cumulative; timings get window gauges
    lines = sm.prometheus_lines()
    text = "\n".join(lines)
    assert "# TYPE nebula_reqs counter" in text
    assert "nebula_reqs_total 2" in text
    assert "nebula_reqs_p95_60s" not in text
    assert "nebula_lat_us_p95_60s" in text
    assert "nebula_lat_us_count_total 2" in text
    # lifetime totals survive window expiry
    clock[0] += 7200
    assert "nebula_reqs_total 2" in "\n".join(sm.prometheus_lines())
    assert sm.read_stats("reqs.sum.60") == 0.0


# ------------------------------------------------------- RPC round-trip

class _EchoSvc:
    def ping(self, x):
        with tracer.span("proc.work", x=x):
            return x + 1


def test_trace_context_rpc_roundtrip_and_reconnect():
    """The envelope carries (trace_id, span_id); the server's remote
    fragment grafts back under the rpc.call span — including after a
    server restart mid-trace (retry/reconnect)."""
    from nebula_tpu.rpc import RpcServer, proxy

    server = RpcServer().register("echo", _EchoSvc()).start()
    port = server.port
    cli = proxy(server.addr, "echo", timeout=2.0, dedicated=True)
    h = tracer.begin("query", force=True)
    assert cli.ping(1) == 2
    # restart the server on the same port: the next traced call rides
    # the reconnect path and must still join the tree
    server.stop()
    server2 = RpcServer(port=port).register("echo", _EchoSvc()).start()
    try:
        assert cli.ping(5) == 6
        trace = h.finish(ok=True)
        by_name = {}
        for s in trace["spans"]:
            by_name.setdefault(s["name"], []).append(s)
        assert len(by_name["rpc.call"]) == 2
        assert len(by_name["echo.ping"]) == 2      # remote roots
        assert len(by_name["proc.work"]) == 2      # server-side child
        ids = {s["span_id"] for s in trace["spans"]}
        # the remote fragments are JOINED: their roots parent under the
        # local rpc.call spans, their children under them
        for remote_root in by_name["echo.ping"]:
            assert remote_root["parent_id"] in \
                {s["span_id"] for s in by_name["rpc.call"]}
        for child in by_name["proc.work"]:
            assert child["parent_id"] in \
                {s["span_id"] for s in by_name["echo.ping"]}
        assert ids  # sanity
    finally:
        cli.close()
        server2.stop()


def test_untraced_rpc_stays_4_tuple():
    """No trace -> classic envelope, classic 2-tuple response (zero
    overhead and wire-compat for untraced calls)."""
    from nebula_tpu.rpc import RpcServer, proxy
    from nebula_tpu.rpc import wire

    seen = {}
    orig = wire.encode

    server = RpcServer().register("echo", _EchoSvc()).start()
    cli = proxy(server.addr, "echo", timeout=2.0, dedicated=True)
    try:
        def spy(obj):
            if isinstance(obj, tuple) and obj and obj[0] == "echo":
                seen["req_len"] = len(obj)
            return orig(obj)

        wire.encode = spy
        try:
            assert cli.ping(1) == 2
        finally:
            wire.encode = orig
        assert seen["req_len"] == 4
    finally:
        cli.close()
        server.stop()


# -------------------------------------------------------- PROFILE e2e

@pytest.fixture
def small_cluster():
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.engine_tpu import TpuGraphEngine

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    for s in ("CREATE SPACE tr(partition_num=2)", "USE tr",
              "CREATE TAG person(age int)", "CREATE EDGE knows(w int)",
              "INSERT VERTEX person(age) VALUES 1:(5), 2:(6), 3:(7), 4:(8)",
              "INSERT EDGE knows(w) VALUES 1 -> 2:(3), 2 -> 3:(4), "
              "1 -> 3:(9), 3 -> 4:(1)"):
        r = conn.execute(s)
        assert r.ok(), (s, r.error_msg)
    yield cluster, conn, tpu


def test_profile_go_identity_and_span_tree(small_cluster):
    """PROFILE GO returns the same rows as plain GO plus a span tree
    containing the dispatcher-window span (acceptance criterion)."""
    cluster, conn, tpu = small_cluster
    q = "GO 2 STEPS FROM 1 OVER knows YIELD knows._dst, knows.w"
    plain = conn.execute(q)
    prof = conn.execute("PROFILE " + q)
    assert plain.ok() and prof.ok()
    assert sorted(plain.rows) == sorted(prof.rows)
    assert plain.trace_id == "" and plain.trace_spans is None
    assert prof.trace_id and prof.trace_spans
    names = {s[2] for s in prof.trace_spans}
    assert "dispatcher.window" in names, names
    assert {"query", "parse", "exec.go", "kernel",
            "materialize"} <= names, names
    # device-served: the root carries the serve mode
    root = [s for s in prof.trace_spans if s[2] == "query"][0]
    assert root[5].get("mode") in ("sparse", "dense")
    # the full trace is in the ring, and renders
    t = tracer.ring.get(prof.trace_id)
    assert t is not None
    rows = render_tree(t)
    assert rows[0][0] == "query" and len(rows) == len(prof.trace_spans)


def test_profile_pipe_aggregate_identity(small_cluster):
    cluster, conn, tpu = small_cluster
    q = ("GO 2 STEPS FROM 1 OVER knows YIELD knows.w AS w "
         "| YIELD COUNT(*) AS n, SUM($-.w) AS s")
    plain = conn.execute(q)
    prof = conn.execute("PROFILE " + q)
    assert plain.ok() and prof.ok(), (plain.error_msg, prof.error_msg)
    assert plain.rows == prof.rows
    assert prof.trace_spans


def test_profile_is_not_a_keyword(small_cluster):
    """An identifier named `profile` still parses (PROFILE is a
    statement prefix, not a reserved word)."""
    cluster, conn, tpu = small_cluster
    r = conn.execute("CREATE TAG profile(x int)")
    assert r.ok(), r.error_msg
    r = conn.execute("YIELD 1 AS profile")
    assert r.ok() and r.columns == ["profile"]


def test_sample_rate_flag_traces_plain_queries(small_cluster):
    cluster, conn, tpu = small_cluster
    # a private ring + a drained armed counter make this airtight:
    # the trace MUST come from rate sampling of THIS query (the
    # process ring may be full of flight-recorder-armed samples from
    # earlier tests, and any leftover armed count would also sample)
    ring0, armed0 = tracer.ring, tracer.armed()
    tracer.ring = TraceRing(16)
    tracer.arm(0)
    assert graph_flags.set("trace_sample_rate", 1.0)
    try:
        r = conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
        assert r.ok()
        # sampled by rate, NOT profiled: ring yes, response no
        assert r.trace_spans is None
        traces = tracer.ring.list(limit=4)
        assert traces, "rate-sampled query left no trace"
        assert traces[0]["tags"].get("feature") == "go"
    finally:
        graph_flags.set("trace_sample_rate", 0.0)
        tracer.ring = ring0
        tracer.arm(armed0)
    assert tracer.sample_rate == 0.0   # flag watcher applied


def test_slow_query_log_threshold(small_cluster):
    cluster, conn, tpu = small_cluster
    svc = cluster.service
    n0 = len(svc.slow_log)
    assert graph_flags.set("slow_query_threshold_ms", 0.0001)
    try:
        conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
    finally:
        graph_flags.set("slow_query_threshold_ms", 500)
    assert len(svc.slow_log) > n0
    entry = svc.slow_log.snapshot()[0]
    assert "GO FROM 1" in entry["stmt"] and entry["latency_us"] > 0
    # back at the default threshold fast queries stay out
    n1 = len(svc.slow_log)
    conn.execute("YIELD 1")
    assert len(svc.slow_log) == n1


def test_degraded_serve_is_tagged_in_trace(small_cluster):
    """A device failure injected under a PROFILEd query degrades to
    the CPU pipe AND tags the trace root (the --chaos contract)."""
    from nebula_tpu.common.faults import faults
    cluster, conn, tpu = small_cluster
    tpu.sparse_edge_budget = 0   # pin dense: kernel.launch is on-path
    q = "PROFILE GO 2 STEPS FROM 1 OVER knows YIELD knows._dst"
    base = conn.execute(q)
    assert base.ok()
    faults.set_plan("kernel.launch:n=4")
    try:
        r = conn.execute(q)
    finally:
        faults.clear()
    assert r.ok(), r.error_msg                  # never a client error
    assert sorted(r.rows) == sorted(base.rows)  # CPU pipe identical
    t = tracer.ring.get(r.trace_id)
    assert t is not None and "degraded" in t["tags"], t["tags"]


def test_active_queries_visible_mid_flight(small_cluster):
    cluster, conn, tpu = small_cluster
    svc = cluster.service
    seen = {}
    barrier = threading.Event()
    orig = svc.engine.execute

    def slow_execute(session, text):
        if text.startswith("GO"):
            seen["active"] = svc.active_queries.snapshot()
            barrier.set()
        return orig(session, text)

    svc.engine.execute = slow_execute
    try:
        conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
    finally:
        svc.engine.execute = orig
    assert barrier.is_set()
    assert any("GO FROM 1" in a["stmt"] for a in seen["active"])


def test_console_renders_profile_tree(small_cluster, capsys):
    from nebula_tpu.console import Console
    cluster, conn, tpu = small_cluster
    console = Console(conn)
    assert console.run_statement(
        "PROFILE GO FROM 1 OVER knows YIELD knows._dst")
    out = capsys.readouterr().out
    assert "| span" in out and "dispatcher.window" in out
    assert "Trace " in out and "spans)" in out


def test_profile_does_not_leak_into_shared_engine_profile(small_cluster):
    """attach_trace must not write into the engine's shared
    last_profile dict (one session's span tree leaking into other
    sessions' responses)."""
    cluster, conn, tpu = small_cluster
    r = conn.execute("PROFILE GO FROM 1 OVER knows YIELD knows._dst")
    assert r.ok() and r.trace_spans
    assert "trace_spans" not in (tpu.last_profile or {})
    assert "trace_id" not in (tpu.last_profile or {})
    r2 = conn.execute("GO FROM 1 OVER knows YIELD knows._dst")
    assert r2.trace_spans is None and r2.trace_id == ""


def test_pool_retry_safe_sees_through_profile_prefix():
    from nebula_tpu.client.pool import Session
    assert Session._retry_safe("PROFILE GO FROM 1 OVER e")
    assert Session._retry_safe("PROFILE\tGO FROM 1 OVER e")
    assert not Session._retry_safe(
        "PROFILE INSERT EDGE e(w) VALUES 1 -> 2:(1)")
    # the prefix is only valid on the FIRST statement (parser rule)
    assert not Session._retry_safe(
        "GO FROM 1 OVER e; PROFILE GO FROM 1 OVER e")


def test_traces_endpoint_follows_ring_swap():
    """/traces must resolve tracer.ring per request — soak --chaos
    swaps in a private ring and the endpoint must follow it back."""
    from nebula_tpu.common.tracing import TraceRing
    from nebula_tpu.webservice import WebService
    ws = WebService("swap-test")
    ws.register_observability()
    port = ws.start()
    try:
        old = tracer.ring
        tracer.ring = TraceRing(8)
        try:
            tracer.ring.add({"trace_id": "swapped", "name": "q",
                             "t0_us": 0, "dur_us": 5, "tags": {},
                             "spans": []})
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/traces?id=swapped") as r:
                assert json.loads(r.read())["trace_id"] == "swapped"
        finally:
            tracer.ring = old
    finally:
        ws.stop()


def test_profile_prefix_is_comment_aware(small_cluster):
    """The text sniff must see the same first token the lexer does: a
    leading comment before PROFILE still yields a trace."""
    from nebula_tpu.common.tracing import split_profile_prefix
    assert split_profile_prefix("# hi\nPROFILE GO") == (True, "GO")
    assert split_profile_prefix("/* x */ PROFILE\nGO") == (True, "GO")
    assert split_profile_prefix("// c\nGO FROM 1 OVER e")[0] is False
    cluster, conn, tpu = small_cluster
    r = conn.execute(
        "# comment\nPROFILE GO FROM 1 OVER knows YIELD knows._dst")
    assert r.ok(), r.error_msg
    assert r.trace_spans, "PROFILE behind a comment must still trace"


def test_use_none_detaches_leader_trace():
    """Serving an UNSAMPLED request must not record spans or
    degradation tags into the (sampled) leader's own trace."""
    t = Tracer()
    h = t.begin("query", force=True)
    with t.span("exec.go"):
        with t.use(None):          # an unsampled waiter's context
            assert not t.active()
            t.add_span("kernel", 100.0)
            t.tag_root("degraded", "cpu_retry:go")
        assert t.active()
        with t.span("materialize"):
            pass
    trace = h.finish()
    names = [s["name"] for s in trace["spans"]]
    assert "kernel" not in names and "materialize" in names
    assert "degraded" not in trace["tags"]
