"""Batched lane-matrix traversal kernels (the dispatcher's shared
device programs)."""
def test_multi_hop_masks_batch_identity():
    """The lane-matrix batched mask kernel must produce EXACTLY the
    per-query final-hop masks the single-query multi_hop emits, over a
    random multi-type graph with invalid edges, for 1/2/3 steps."""
    import jax.numpy as jnp
    import numpy as np
    from nebula_tpu.engine_tpu import traverse

    rng = np.random.default_rng(17)
    P, cap_v, cap_e, B = 4, 64, 128, 5
    src = rng.integers(0, cap_v, (P, cap_e)).astype(np.int32)
    etype = rng.choice([1, 2, -1], (P, cap_e)).astype(np.int32)
    valid = rng.random((P, cap_e)) < 0.7
    dst_p = rng.integers(0, P, (P, cap_e))
    dst_l = rng.integers(0, cap_v, (P, cap_e))
    gidx = (dst_p * cap_v + dst_l).astype(np.int32)
    kern = traverse.build_kernel(src, etype, valid, gidx, P, cap_v)[0]
    gsrc = (np.repeat(np.arange(P), cap_e) * cap_v
            + src.reshape(-1)).astype(np.int32)
    gdst = np.where(valid.reshape(-1), gidx.reshape(-1),
                    P * cap_v).astype(np.int64)
    ak, chunk, group = traverse.build_aligned(gsrc, etype.reshape(-1),
                                              gdst, P * cap_v)
    f0s = np.zeros((B, P, cap_v), bool)
    for b in range(B):
        f0s[b, rng.integers(0, P, 3), rng.integers(0, cap_v, 3)] = True
    for req_list in ([1], [1, 2], [2, -1]):
        req = jnp.asarray(traverse.pad_edge_types(req_list))
        for steps in (1, 2, 3):
            got = np.asarray(traverse.multi_hop_masks_batch(
                jnp.asarray(f0s), jnp.int32(steps), ak, kern, req,
                chunk=chunk, group=group))
            for b in range(B):
                _, want = traverse.multi_hop(jnp.asarray(f0s[b]),
                                             jnp.int32(steps), kern, req)
                assert (got[b] == np.asarray(want)).all(), \
                    (req_list, steps, b)
