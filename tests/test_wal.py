"""Native WAL tests — mirroring the reference's FileBasedWalTest matrix
(append/reopen recovery, multi-segment roll, rollback, iterator ranges,
torn-tail truncation, TTL cleanup)."""
import os
import struct

import pytest

from nebula_tpu.kvstore.wal import Wal


@pytest.fixture
def wdir(tmp_path):
    return str(tmp_path / "wal")


def test_empty(wdir):
    w = Wal(wdir)
    assert w.first_log_id == 0
    assert w.last_log_id == 0
    assert w.last_log_term == 0
    assert list(w.iterate(1)) == []
    w.close()


def test_append_and_iterate(wdir):
    w = Wal(wdir)
    for i in range(1, 101):
        assert w.append(i, 1, 0, f"log-{i}".encode())
    assert w.first_log_id == 1
    assert w.last_log_id == 100
    entries = list(w.iterate(1))
    assert len(entries) == 100
    assert entries[0].data == b"log-1"
    assert entries[99].data == b"log-100"
    # sub-range
    sub = list(w.iterate(40, 42))
    assert [e.log_id for e in sub] == [40, 41, 42]
    w.close()


def test_non_consecutive_append_rejected(wdir):
    w = Wal(wdir)
    assert w.append(1, 1, 0, b"a")
    assert not w.append(3, 1, 0, b"c")
    assert w.last_log_id == 1
    w.close()


def test_reopen_recovers(wdir):
    w = Wal(wdir)
    for i in range(1, 51):
        w.append(i, (i // 10) + 1, 0, b"x" * i)
    w.close()
    w2 = Wal(wdir)
    assert w2.last_log_id == 50
    assert w2.last_log_term == 6
    assert w2.log_term(9) == 1
    assert w2.log_term(10) == 2
    entries = list(w2.iterate(1))
    assert len(entries) == 50
    assert entries[-1].data == b"x" * 50
    w2.close()


def test_multi_segment_roll_and_reopen(wdir):
    # tiny segment size forces many files
    w = Wal(wdir, max_file_size=512)
    for i in range(1, 201):
        w.append(i, 7, 0, b"payload-%d" % i)
    w.close()
    files = [f for f in os.listdir(wdir) if f.endswith(".wal")]
    assert len(files) > 3
    w2 = Wal(wdir, max_file_size=512)
    assert w2.last_log_id == 200
    assert [e.log_id for e in w2.iterate(150, 155)] == list(range(150, 156))
    w2.close()


def test_rollback(wdir):
    w = Wal(wdir)
    for i in range(1, 21):
        w.append(i, 1, 0, b"d%d" % i)
    assert w.rollback(12)
    assert w.last_log_id == 12
    # append continues from the rollback point with a new term
    assert w.append(13, 2, 0, b"new13")
    entries = list(w.iterate(12, 13))
    assert entries[0].data == b"d12"
    assert entries[1].data == b"new13"
    assert entries[1].term == 2
    w.close()


def test_rollback_across_segments(wdir):
    w = Wal(wdir, max_file_size=256)
    for i in range(1, 101):
        w.append(i, 1, 0, b"seg-%03d" % i)
    assert w.rollback(30)
    assert w.last_log_id == 30
    w.close()
    w2 = Wal(wdir, max_file_size=256)
    assert w2.last_log_id == 30
    assert len(list(w2.iterate(1))) == 30
    w2.close()


def test_rollback_to_zero_resets(wdir):
    w = Wal(wdir)
    for i in range(1, 6):
        w.append(i, 3, 0, b"z")
    assert w.rollback(0)
    assert w.last_log_id == 0
    assert w.append(1, 4, 0, b"fresh")
    assert w.last_log_term == 4
    w.close()


def test_torn_tail_truncated_on_reopen(wdir):
    w = Wal(wdir)
    for i in range(1, 11):
        w.append(i, 1, 0, b"entry-%d" % i)
    w.close()
    # corrupt: chop bytes off the end of the (single) segment file
    files = sorted(f for f in os.listdir(wdir) if f.endswith(".wal"))
    path = os.path.join(wdir, files[-1])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)
    w2 = Wal(wdir)
    assert w2.last_log_id == 9          # torn record 10 dropped
    assert w2.append(10, 2, 0, b"rewritten")
    assert list(w2.iterate(10))[0].data == b"rewritten"
    w2.close()


def test_corrupt_crc_stops_scan(wdir):
    w = Wal(wdir)
    for i in range(1, 6):
        w.append(i, 1, 0, b"abcdefgh")
    w.close()
    files = sorted(f for f in os.listdir(wdir) if f.endswith(".wal"))
    path = os.path.join(wdir, files[-1])
    # flip a byte inside record 3's payload:
    # header 16 + record overhead 36 + payload 8 = 44/record
    rec = 16 + 2 * 44 + 28 + 2
    with open(path, "r+b") as f:
        f.seek(rec)
        b = f.read(1)
        f.seek(rec)
        f.write(bytes([b[0] ^ 0xFF]))
    w2 = Wal(wdir)
    assert w2.last_log_id == 2          # 3 is corrupt; 4,5 unreachable
    w2.close()


def test_ttl_cleanup(wdir):
    w = Wal(wdir, ttl_secs=0, max_file_size=256)
    for i in range(1, 101):
        w.append(i, 1, 0, b"ttl-%03d" % i)
    n_before = len([f for f in os.listdir(wdir) if f.endswith(".wal")])
    assert n_before > 2
    removed = w.clean_ttl()
    assert removed == n_before - 1       # active segment survives
    assert w.last_log_id == 100          # tail intact
    assert w.first_log_id > 1            # head evicted
    w.close()


def test_clean_before_drops_only_sealed_prefix(wdir):
    w = Wal(wdir, max_file_size=256)
    for i in range(1, 101):
        w.append(i, 1, 0, b"cb-%03d" % i)
    n_before = len([f for f in os.listdir(wdir) if f.endswith(".wal")])
    assert n_before > 3
    removed = w.clean_before(60)
    assert removed > 0
    # every record >= 60 survives; nothing above the anchor is touched
    assert w.first_log_id <= 60
    assert w.last_log_id == 100
    assert [e.log_id for e in w.iterate(60, 62)] == [60, 61, 62]
    # idempotent: a second call with the same anchor is a no-op
    assert w.clean_before(60) == 0
    w.close()
    # survives reopen: the compacted WAL recovers [first..100]
    w2 = Wal(wdir, max_file_size=256)
    assert w2.last_log_id == 100
    assert w2.first_log_id <= 60
    w2.close()


def test_clean_before_never_touches_active_segment(wdir):
    w = Wal(wdir)                       # single (active) segment
    for i in range(1, 21):
        w.append(i, 1, 0, b"x%d" % i)
    # an anchor past the end must not drop the active segment
    assert w.clean_before(10 ** 9) == 0
    assert w.first_log_id == 1
    assert w.last_log_id == 20
    w.close()


def test_torn_tail_fault_point_recovers_on_reopen(wdir):
    """Satellite: the `wal.torn_tail` fault point truncates trailing
    bytes at close — the next open must CRC-truncate the torn record
    and recover the prefix (the native torn-tail path proven
    end-to-end from Python, docs/manual/9-robustness.md)."""
    from nebula_tpu.common.faults import faults
    w = Wal(wdir)
    for i in range(1, 11):
        w.append(i, 1, 0, b"tt-%d" % i)
    try:
        faults.set_plan("wal.torn_tail:n=1")
        w.close()
        assert faults.counts().get("wal.torn_tail") == 1
    finally:
        faults.reset()
    w2 = Wal(wdir)
    assert w2.last_log_id == 9          # torn record 10 dropped
    assert w2.append(10, 2, 0, b"rewritten")
    assert list(w2.iterate(10))[0].data == b"rewritten"
    w2.close()


def test_cluster_field_roundtrip(wdir):
    w = Wal(wdir)
    w.append(1, 1, 12345, struct.pack("<q", -99))
    e = list(w.iterate(1))[0]
    assert e.cluster == 12345
    assert struct.unpack("<q", e.data)[0] == -99
    w.close()


def test_large_payload(wdir):
    w = Wal(wdir)
    blob = os.urandom(1 << 20)
    w.append(1, 1, 0, blob)
    assert list(w.iterate(1))[0].data == blob
    w.close()
    w2 = Wal(wdir)
    assert list(w2.iterate(1))[0].data == blob
    w2.close()
