"""Wire-protocol conformance: the codec must match the frozen v1 spec
(docs/manual/6-wire-protocol.md + wire-vectors.json) byte-for-byte in
both directions, and the registry assignment must never drift — the
spec is what lets a non-Python client speak to graphd (the capability
the reference gets from thrift IDL, src/interface/graph.thrift)."""
import dataclasses
import enum
import json
import os

import pytest

from nebula_tpu.common.status import ErrorCode, Status, StatusOr
from nebula_tpu.rpc import wire

VECTORS = os.path.join(os.path.dirname(__file__), "..", "docs", "manual",
                       "wire-vectors.json")

with open(VECTORS) as f:
    SPEC = json.load(f)

wire.encode(None)   # force registry init
_BY_NAME = {t.__name__: t for t in wire._registry}


def from_json(v):
    """Inverse of the vector file's JSON rendering (spec §6)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, list):
        return [from_json(x) for x in v]
    assert isinstance(v, dict), v
    if "$bytes" in v:
        return bytes.fromhex(v["$bytes"])
    if "$tuple" in v:
        return tuple(from_json(x) for x in v["$tuple"])
    if "$map" in v:
        return {from_json(k): from_json(x) for k, x in v["$map"]}
    if "$enum" in v:
        return _BY_NAME[v["$enum"]](v["value"])
    if "$struct" in v:
        t = _BY_NAME[v["$struct"]]
        fields = [from_json(x) for x in v["fields"]]
        return t(*fields)   # StatusOr's __init__ is (status, value) too
    raise AssertionError(f"unknown rendering {v}")


@pytest.mark.parametrize("vec", SPEC["vectors"], ids=lambda v: v["name"])
def test_vector_roundtrip(vec):
    value = from_json(vec["value"])
    raw = bytes.fromhex(vec["hex"])
    # decode: frozen bytes -> the documented value
    decoded = wire.decode(raw)
    if isinstance(value, StatusOr):
        assert decoded.status.code == value.status.code
        assert decoded._value == value._value
    elif isinstance(value, Status):
        assert decoded.code == value.code and decoded.msg == value.msg
    else:
        assert decoded == value, vec["name"]
    # encode: the value -> the exact frozen bytes (canonical encoding)
    assert wire.encode(value).hex() == vec["hex"], vec["name"]


def test_registry_assignment_frozen():
    """Registry ids are positional and append-only (spec §4): the live
    registry must contain the spec's table as an exact PREFIX."""
    live = [t.__name__ for t in wire._registry]
    spec = [e["name"] for e in SPEC["registry"]]
    assert live[:len(spec)] == spec, (
        "wire registry ids drifted from docs/manual/wire-vectors.json — "
        "ids are frozen; append new types at the END and regenerate the "
        "vector file's registry table")
    for e in SPEC["registry"]:
        t = _BY_NAME[e["name"]]
        if "fields" not in e:
            continue
        if dataclasses.is_dataclass(t):
            assert [f.name for f in dataclasses.fields(t)] == e["fields"], \
                f"{e['name']} field order changed — wire format break"


def test_registry_covers_all_defaults():
    """Every registered type appears in the spec table (no silent
    additions without a vector-file regeneration)."""
    spec_names = {e["name"] for e in SPEC["registry"]}
    live_names = {t.__name__ for t in wire._registry}
    assert live_names == spec_names, (
        live_names - spec_names, spec_names - live_names)
