"""Tier-1-safe write-path observatory smoke: `bench.py --writes
--trim` in a SUBPROCESS on XLA:CPU — the full proof tier: disarmed
byte-identity, the per-stage timeline (execute → fanout → wal_append →
replicate → commit_apply → ring_publish → delta_apply/repack) with
exemplars, the ack-to-visible watermark, zero acked-write loss through
a genuine change-ring overrun (overrun → poison → repack, one
attributed chain in the ring_overrun flight bundle), the replicated
group-commit/fsync metrics, the fsync_stall + visibility_stall drills
and the ≤3% seam-cost contract (docs/manual/10-observability.md,
"Write-path observatory"). The subprocess keeps the parent's JAX
backend state out of the picture, exactly like the consistency/chaos/
cluster smoke tiers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def write_smoke(tmp_path_factory):
    out = tmp_path_factory.mktemp("writes") / "WRITE_smoke.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_WRITES_SEED"] = "29"     # deterministic graph/draws
    env["BENCH_WRITES_OUT"] = str(out)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--writes", "--trim"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


def test_writes_all_gates_green(write_smoke):
    assert write_smoke["ok"] is True, write_smoke["gates"]
    assert all(write_smoke["gates"].values()), write_smoke["gates"]


def test_writes_disarmed_left_no_trace(write_smoke):
    assert write_smoke["disarmed"]["metric_lines"] == 0


def test_writes_stage_timeline_populated(write_smoke):
    st = write_smoke["stages"]
    for stage in ("execute", "fanout", "commit_apply",
                  "ring_publish", "delta_apply"):
        assert st[stage]["count"] > 0, (stage, st)
    # at least one synchronous stage carries a trace exemplar
    assert any((st[s] or {}).get("exemplars", 0) > 0
               for s in ("execute", "fanout", "commit_apply")), st


def test_writes_no_acked_write_lost(write_smoke):
    assert write_smoke["durability"]["missing"] == []
    assert write_smoke["durability"]["edges_tracked"] > 0
    assert write_smoke["overrun"]["missing"] == []
    assert write_smoke["ack_to_visible_ms"]["count"] > 0


def test_writes_overrun_chain_attributed(write_smoke):
    counts = write_smoke["overrun"]["ledger_counts"]
    assert counts.get("overrun", 0) >= 1, counts
    assert counts.get("poison", 0) >= 1, counts
    assert counts.get("repack", 0) >= 1, counts


def test_writes_seam_cost_within_contract(write_smoke):
    oh = write_smoke["overhead"]
    assert oh["seam_frac"] <= 0.03, oh
