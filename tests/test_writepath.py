"""Write-path observatory tests (common/writepath.py; docs/manual/
10-observability.md, "Write-path observatory"): the per-stage write
timeline, the ack-to-visible watermark (delta apply AND repack
advances), the overrun -> poison -> repack cause chain, the /snapshots
lifecycle surface and the write_obs_enabled disarm byte-identity
contract."""
import time

import pytest

from nba_fixture import load_nba
from nebula_tpu.cluster import InProcCluster
from nebula_tpu.common import writepath as wp
from nebula_tpu.common.faults import faults
from nebula_tpu.common.flags import graph_flags, storage_flags
from nebula_tpu.common.flight import recorder as flight_rec
from nebula_tpu.common.stats import StatsManager
from nebula_tpu.engine_tpu import TpuGraphEngine


@pytest.fixture()
def rig(monkeypatch):
    """Armed in-proc cluster with a PRIVATE StatsManager behind the
    writepath module (tier-1 shares one process-global registry; the
    swap keeps every count in this test's hands) and pristine
    watermark/ledger state."""
    priv = StatsManager()
    monkeypatch.setattr(wp, "stats", priv)
    wp.reset()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    _, conn = load_nba(cluster)
    sid = cluster.meta.get_space("nba").value().space_id
    yield cluster, conn, tpu, sid, priv
    wp.reset()


def _hist_count(priv, name):
    h = priv.histogram_snapshot(name)
    return int(h["count"]) if h else 0


def test_stage_timeline_on_write(rig):
    """One replicated-shape write through the in-proc stack populates
    every synchronous seam plus the async visibility stages."""
    cluster, conn, tpu, sid, priv = rig
    conn.must("GO FROM 100 OVER like")           # snapshot + cursor up
    conn.must("INSERT EDGE like(likeness) VALUES 101 -> 100:(70.0)")
    conn.must("GO FROM 101 OVER like")           # pull -> delta apply
    for stage in ("execute", "fanout", "commit_apply",
                  "ring_publish", "delta_apply"):
        assert _hist_count(priv, f"write.stage.{stage}_us") > 0, stage
    assert priv.lifetime_total("write.acked") > 0
    assert priv.lifetime_total("write.visible") > 0


def test_profile_renders_write_stages(rig):
    """PROFILE on a mutation renders the per-stage cost block the way
    reads already do (the appended write_* ledger fields)."""
    _, conn, _, _, _ = rig
    r = conn.must("PROFILE INSERT EDGE like(likeness) "
                  "VALUES 102 -> 100:(41.0)")
    ws = (r.profile or {}).get("write_stages") or {}
    assert {"execute", "fanout", "commit_apply"} <= set(ws), ws
    assert all(v > 0 for v in ws.values()), ws


def test_watermark_advances_on_delta_and_repack(rig):
    """`note_visible` fires from BOTH visibility paths: the in-place
    delta apply (cause delta) and a full host repack (cause repack)."""
    cluster, conn, tpu, sid, priv = rig
    conn.must("GO FROM 100 OVER like")
    conn.must("INSERT EDGE like(likeness) VALUES 103 -> 100:(33.0)")
    conn.must("GO FROM 103 OVER like")
    wmv = wp.watermark.stats_view()
    assert wmv[sid]["visible"] > 0
    assert wmv[sid]["last_cause"] == "delta"
    assert _hist_count(priv, "write.ack_to_visible_ms") > 0
    # a second acked write made visible by a REPACK, not a delta pull
    conn.must("INSERT EDGE like(likeness) VALUES 104 -> 100:(34.0)")
    assert wp.watermark.stats_view()[sid]["pending"] > 0
    tpu._kick_repack(sid, cause="test")
    deadline = time.time() + 10
    while (wp.watermark.stats_view()[sid]["pending"] > 0
           and time.time() < deadline):
        time.sleep(0.05)
    wmv = wp.watermark.stats_view()
    assert wmv[sid]["pending"] == 0, wmv
    assert wmv[sid]["last_cause"] == "repack"
    events = wp.snapshots.view()["spaces"][sid]
    assert any(ev["event"] == "repack" for ev in events)


def test_overrun_cause_attribution(rig):
    """`ring.overrun:n=1` forces the decline: the lifecycle ledger
    records overrun(injected) -> poison(ring_overrun) ->
    repack(ring_overrun) as ONE attributed chain, and the ring_overrun
    flight bundle's "writepath" collector carries that ledger."""
    cluster, conn, tpu, sid, priv = rig
    conn.must("GO FROM 100 OVER like")
    flight_rec.reset()
    faults.set_plan("ring.overrun:n=1")
    try:
        conn.must("INSERT EDGE like(likeness) VALUES 105 -> 100:(5.0)")
        conn.must("GO FROM 105 OVER like")       # pull hits the fault
        deadline = time.time() + 10
        while (wp.snapshots.view()["counts"].get("repack", 0) == 0
               and time.time() < deadline):
            conn.must("GO FROM 105 OVER like")
            time.sleep(0.05)
    finally:
        faults.clear()
    assert faults.counts().get("ring.overrun") == 1
    assert priv.lifetime_total("write.ring.overrun") >= 1
    causes = {}
    for ev in wp.snapshots.view()["spaces"][sid]:
        causes.setdefault(ev["event"], set()).add(ev.get("cause"))
    assert "injected" in causes.get("overrun", ()), causes
    assert "ring_overrun" in causes.get("poison", ()), causes
    assert "ring_overrun" in causes.get("repack", ()), causes
    assert flight_rec.flush()
    bundles = [b for b in flight_rec.bundles
               if b["trigger"] == "ring_overrun"]
    assert bundles
    col = bundles[-1]["collectors"]["writepath"]
    assert col["ledger"]["counts"].get("overrun", 0) >= 1
    # serving survived the poison: the edge reads back post-repack
    r = conn.must("GO FROM 105 OVER like YIELD like._dst")
    assert (100,) in r.rows


def test_snapshots_view_shape(rig):
    """/snapshots body: watermark + lifecycle ledger + ring occupancy
    + per-engine snapshot status (graphd and storaged both serve it
    through the webservice built-in)."""
    cluster, conn, tpu, sid, priv = rig
    conn.must("GO FROM 100 OVER like")
    body = wp.snapshots_view()
    assert body["enabled"] is True
    assert {"watermark", "ledger", "rings", "engines"} <= set(body)
    assert sid in body["rings"]
    assert body["rings"][sid]["cap_ops"] > 0
    eng = next(st for st in body["engines"]
               if str(sid) in st["spaces"])
    sp = eng["spaces"][str(sid)]
    assert {"write_version", "stale", "device_bytes",
            "repacking"} <= set(sp)
    assert {"rebuilds", "bg_repacks", "delta_applies"} \
        <= set(eng["counters"])
    # the lifecycle ledger saw the build
    assert wp.snapshots.view()["counts"].get("build", 0) >= 1


def test_disarm_byte_identity(monkeypatch):
    """write_obs_enabled=false BEFORE any armed traffic: the whole
    load + write + read loop registers ZERO families on the stats
    surface, /snapshots reports only {"enabled": false} and the gauge
    source is empty — the heat_enabled/profile_hz=0 idiom."""
    priv = StatsManager()
    monkeypatch.setattr(wp, "stats", priv)
    wp.reset()
    graph_flags.set("write_obs_enabled", False)
    storage_flags.set("write_obs_enabled", False)
    try:
        assert not wp.enabled()
        tpu = TpuGraphEngine()
        cluster = InProcCluster(tpu_engine=tpu)
        _, conn = load_nba(cluster)
        for i in range(6):
            conn.must(f"INSERT EDGE like(likeness) VALUES "
                      f"106 -> {100 + i}:(9.0)")
            conn.must("GO FROM 106 OVER like")
        assert not any(n.startswith(("write.", "snapshot.", "wal."))
                       for n in priv.names())
        assert wp.snapshots_view() == {"enabled": False}
        assert wp.gauges() == {}
        # the PR 12 cost ledger keeps its own contract: PROFILE still
        # renders the write stages from the unconditional charges
        r = conn.must("PROFILE INSERT EDGE like(likeness) "
                      "VALUES 107 -> 100:(8.0)")
        ws = (r.profile or {}).get("write_stages") or {}
        assert {"execute", "fanout", "commit_apply"} <= set(ws), ws
    finally:
        graph_flags.set("write_obs_enabled", True)
        storage_flags.set("write_obs_enabled", True)
        wp.reset()


def test_nested_fanout_charges_once(rig):
    """DELETE VERTEX fans edge deletes through the same StorageClient;
    the nested timed_stage("fanout") extents must not double-charge
    (the reentrancy guard)."""
    cluster, conn, tpu, sid, priv = rig
    n0 = _hist_count(priv, "write.stage.fanout_us")
    conn.must("DELETE VERTEX 110")
    assert _hist_count(priv, "write.stage.fanout_us") == n0 + 1
